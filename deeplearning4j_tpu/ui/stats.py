"""Stats collection (reference ``org.deeplearning4j.ui.stats.StatsListener``
+ ``org.deeplearning4j.api.storage.StatsStorage``).

Per-iteration records: score, per-layer parameter/update mean magnitudes and
stddevs, update:param ratios, throughput, device memory. Collection reads
happen on host between steps; heavy reductions are jitted and batched into
ONE device program per sampled iteration (the reference pulls every array to
the host per iteration — on TPU that would stall the pipeline).
"""

from __future__ import annotations

import json
import math
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.train.listeners import TrainingListener


class StatsStorage:
    def put_record(self, record: Dict[str, Any]) -> None:
        raise NotImplementedError

    def records(self) -> List[Dict[str, Any]]:
        raise NotImplementedError


class InMemoryStatsStorage(StatsStorage):
    def __init__(self):
        self._records: List[Dict[str, Any]] = []

    def put_record(self, record):
        self._records.append(record)

    def records(self):
        return list(self._records)


class FileStatsStorage(StatsStorage):
    """JSONL file storage (reference's MapDB ``FileStatsStorage`` analog)."""

    def __init__(self, path: str):
        self.path = path

    def put_record(self, record):
        with open(self.path, "a") as f:
            f.write(json.dumps(record) + "\n")

    def records(self):
        out = []
        try:
            with open(self.path) as f:
                for line in f:
                    out.append(json.loads(line))
        except FileNotFoundError:
            pass
        return out


HIST_BINS = 32


def _leaf_hist(wf):
    """Fixed-bin histogram on device: counts over [min, max]."""
    lo, hi = jnp.min(wf), jnp.max(wf)
    span = jnp.maximum(hi - lo, 1e-12)
    idx = jnp.clip(((wf - lo) / span * HIST_BINS).astype(jnp.int32),
                   0, HIST_BINS - 1)
    counts = jnp.bincount(idx.ravel(), length=HIST_BINS)
    return {"counts": counts, "lo": lo, "hi": hi}


@jax.jit
def _param_stats(params):
    """One fused program: mean |w|, std, l2 AND a full histogram per leaf
    (reference StatsListener records parameter histograms; bincount runs on
    device so only 32 ints per leaf cross to the host)."""
    def leaf(w):
        wf = w.astype(jnp.float32)
        return {"mean_mag": jnp.mean(jnp.abs(wf)), "std": jnp.std(wf),
                "l2": jnp.sqrt(jnp.sum(wf * wf)), "hist": _leaf_hist(wf)}
    return jax.tree.map(leaf, params, is_leaf=lambda x: isinstance(x, jax.Array))


@jax.jit
def _update_stats(params, prev_params):
    """Histogram + mean magnitude of the parameter DELTA since the last
    sampled iteration (reference: update histograms)."""
    def leaf(w, p):
        d = w.astype(jnp.float32) - p.astype(jnp.float32)
        return {"mean_mag": jnp.mean(jnp.abs(d)), "hist": _leaf_hist(d)}
    return jax.tree.map(leaf, params, prev_params,
                        is_leaf=lambda x: isinstance(x, jax.Array))


@jax.jit
def _activation_stats(acts):
    """Mean/std + histogram per sampled layer activation."""
    def leaf(a):
        af = a.astype(jnp.float32)
        return {"mean": jnp.mean(af), "std": jnp.std(af),
                "hist": _leaf_hist(af)}
    return [leaf(a) for a in acts]


def _jsonable(v):
    """Device stats -> JSON-ready (np arrays to lists, scalars to floats)."""
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    if isinstance(v, np.ndarray):
        return v.tolist()
    return float(v)


class StatsListener(TrainingListener):
    def __init__(self, storage: Optional[StatsStorage] = None, frequency: int = 10,
                 collect_histograms: bool = True,
                 collect_activations: bool = False):
        self.storage = storage or InMemoryStatsStorage()
        self.frequency = max(1, int(frequency))
        self.collect_histograms = collect_histograms
        self.collect_activations = collect_activations
        self._last_time = None
        self._prev_params = None
        self._prev_device_params = None

    @staticmethod
    def _group(stats):
        """Nested device stats -> {layer: {param_path: stat_dict}}."""
        def is_stat(v):
            return isinstance(v, dict) and ("mean_mag" in v or "mean" in v)

        grouped: Dict[str, Dict[str, Any]] = {}
        flat = jax.tree_util.tree_flatten_with_path(stats, is_leaf=is_stat)[0]
        for path, val in flat:
            keys = [str(getattr(p, "key", p)) for p in path]
            grouped.setdefault(keys[0], {})["/".join(keys[1:])] = _jsonable(val)
        return grouped

    def iteration_done(self, model, iteration, epoch, score):
        if iteration % self.frequency:
            return
        now = time.time()
        record: Dict[str, Any] = {
            "iteration": iteration,
            "epoch": epoch,
            "timestamp": now,
            "score": float(score),
        }
        if self._last_time is not None:
            record["iterations_per_second"] = self.frequency / max(1e-9, now - self._last_time)
        self._last_time = now
        ts = getattr(model, "train_state", None)
        if ts is not None:
            stats = jax.device_get(_param_stats(ts.params))
            record["params"] = self._group(stats)
            if self.collect_histograms and self._prev_device_params is not None:
                upd = jax.device_get(
                    _update_stats(ts.params, self._prev_device_params))
                record["updates"] = self._group(upd)
            if self.collect_histograms:
                # the train step DONATES its state pytree, so the old
                # buffers die next step — snapshot a device-side copy
                self._prev_device_params = jax.tree.map(jnp.copy, ts.params)
            if self.collect_activations:
                x = getattr(model, "_last_batch_features", None)
                if x is not None and hasattr(model, "feed_forward"):
                    acts = model.feed_forward(x)[1:]
                    record["activations"] = [
                        _jsonable(s) for s in jax.device_get(
                            _activation_stats(acts))]
            grouped = record["params"]
            if self._prev_params is not None:
                ratios = {}
                for layer, pstats in grouped.items():
                    prev = self._prev_params.get(layer, {})
                    for pname, s in pstats.items():
                        if pname in prev and s["mean_mag"] > 0:
                            delta = abs(prev[pname]["mean_mag"] - s["mean_mag"])
                            ratios[f"{layer}/{pname}"] = delta / s["mean_mag"]
                record["update_param_ratios"] = ratios
            self._prev_params = grouped
        try:
            from deeplearning4j_tpu.runtime.profiler import device_memory_stats
            mem = device_memory_stats()
            if mem:
                record["device_memory"] = mem
        except Exception:
            pass
        self.storage.put_record(record)


class RemoteUIStatsStorage(StatsStorage):
    """POST records to a (possibly remote) :class:`UIServer` over HTTP
    (reference ``RemoteUIStatsStorage`` / ``StatsStorageRouter``): run the UI
    in one process/host, train in another, and pass this storage to
    :class:`StatsListener`."""

    def __init__(self, url: str = "http://127.0.0.1:9000"):
        self.url = url.rstrip("/") + "/api/post"
        self._sent: List[Dict[str, Any]] = []

    def put_record(self, record):
        import urllib.error
        import urllib.request
        data = json.dumps(record).encode()
        req = urllib.request.Request(
            self.url, data=data, headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=5).close()
        except urllib.error.HTTPError as e:
            raise IOError(f"UI server rejected record: HTTP {e.code}") from e
        self._sent.append(record)

    def records(self):
        return list(self._sent)
