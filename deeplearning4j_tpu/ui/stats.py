"""Stats collection (reference ``org.deeplearning4j.ui.stats.StatsListener``
+ ``org.deeplearning4j.api.storage.StatsStorage``).

Per-iteration records: score, per-layer parameter/update mean magnitudes and
stddevs, update:param ratios, throughput, device memory. Collection reads
happen on host between steps; heavy reductions are jitted and batched into
ONE device program per sampled iteration (the reference pulls every array to
the host per iteration — on TPU that would stall the pipeline).
"""

from __future__ import annotations

import json
import math
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.train.listeners import TrainingListener


class StatsStorage:
    def put_record(self, record: Dict[str, Any]) -> None:
        raise NotImplementedError

    def records(self) -> List[Dict[str, Any]]:
        raise NotImplementedError


class InMemoryStatsStorage(StatsStorage):
    def __init__(self):
        self._records: List[Dict[str, Any]] = []

    def put_record(self, record):
        self._records.append(record)

    def records(self):
        return list(self._records)


class FileStatsStorage(StatsStorage):
    """JSONL file storage (reference's MapDB ``FileStatsStorage`` analog)."""

    def __init__(self, path: str):
        self.path = path

    def put_record(self, record):
        with open(self.path, "a") as f:
            f.write(json.dumps(record) + "\n")

    def records(self):
        out = []
        try:
            with open(self.path) as f:
                for line in f:
                    out.append(json.loads(line))
        except FileNotFoundError:
            pass
        return out


@jax.jit
def _param_stats(params):
    """One fused program: mean |w|, std, l2 per leaf."""
    def leaf(w):
        wf = w.astype(jnp.float32)
        return {"mean_mag": jnp.mean(jnp.abs(wf)), "std": jnp.std(wf),
                "l2": jnp.sqrt(jnp.sum(wf * wf))}
    return jax.tree.map(leaf, params, is_leaf=lambda x: isinstance(x, jax.Array))


class StatsListener(TrainingListener):
    def __init__(self, storage: Optional[StatsStorage] = None, frequency: int = 10):
        self.storage = storage or InMemoryStatsStorage()
        self.frequency = max(1, int(frequency))
        self._last_time = None
        self._prev_params = None

    def iteration_done(self, model, iteration, epoch, score):
        if iteration % self.frequency:
            return
        now = time.time()
        record: Dict[str, Any] = {
            "iteration": iteration,
            "epoch": epoch,
            "timestamp": now,
            "score": float(score),
        }
        if self._last_time is not None:
            record["iterations_per_second"] = self.frequency / max(1e-9, now - self._last_time)
        self._last_time = now
        ts = getattr(model, "train_state", None)
        if ts is not None:
            stats = jax.device_get(_param_stats(ts.params))
            layers = {}
            flat = jax.tree_util.tree_flatten_with_path(stats)[0]
            # group leaves: path like ('layer_0', 'W', 'mean_mag')
            grouped: Dict[str, Dict[str, Dict[str, float]]] = {}
            for path, val in flat:
                keys = [str(getattr(p, "key", p)) for p in path]
                layer, stat = keys[0], keys[-1]
                pname = "/".join(keys[1:-1])
                grouped.setdefault(layer, {}).setdefault(pname, {})[stat] = float(val)
            record["params"] = grouped
            if self._prev_params is not None:
                ratios = {}
                for layer, pstats in grouped.items():
                    prev = self._prev_params.get(layer, {})
                    for pname, s in pstats.items():
                        if pname in prev and s["mean_mag"] > 0:
                            delta = abs(prev[pname]["mean_mag"] - s["mean_mag"])
                            ratios[f"{layer}/{pname}"] = delta / s["mean_mag"]
                record["update_param_ratios"] = ratios
            self._prev_params = grouped
        try:
            from deeplearning4j_tpu.runtime.profiler import device_memory_stats
            mem = device_memory_stats()
            if mem:
                record["device_memory"] = mem
        except Exception:
            pass
        self.storage.put_record(record)


class RemoteUIStatsStorage(StatsStorage):
    """POST records to a (possibly remote) :class:`UIServer` over HTTP
    (reference ``RemoteUIStatsStorage`` / ``StatsStorageRouter``): run the UI
    in one process/host, train in another, and pass this storage to
    :class:`StatsListener`."""

    def __init__(self, url: str = "http://127.0.0.1:9000"):
        self.url = url.rstrip("/") + "/api/post"
        self._sent: List[Dict[str, Any]] = []

    def put_record(self, record):
        import urllib.error
        import urllib.request
        data = json.dumps(record).encode()
        req = urllib.request.Request(
            self.url, data=data, headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=5).close()
        except urllib.error.HTTPError as e:
            raise IOError(f"UI server rejected record: HTTP {e.code}") from e
        self._sent.append(record)

    def records(self):
        return list(self._sent)
