"""FastText: subword-aware embeddings + supervised text classification.

Rebuild of upstream ``org.deeplearning4j.models.fasttext.FastText`` (a JNI
wrapper over Facebook's fastText in the reference). Here the model itself is
TPU-native: a single embedding table holds word rows and hashed character
n-gram bucket rows; a word's vector is the MEAN of its word row and its
n-gram rows (so out-of-vocabulary words still get vectors — the defining
fastText capability). Both training modes are one jitted donated update:

- unsupervised: skip-gram with negative sampling over subword-composed inputs
- supervised: mean-of-features bag → linear softmax over labels
"""

from __future__ import annotations

import functools
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory, TokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabCache

_FNV_PRIME = 16777619
_FNV_OFFSET = 2166136261


def _fnv1a(s: str) -> int:
    h = _FNV_OFFSET
    for ch in s.encode("utf-8"):
        h = ((h ^ ch) * _FNV_PRIME) & 0xFFFFFFFF
    return h


def char_ngrams(word: str, min_n: int, max_n: int) -> List[str]:
    """fastText-style n-grams of ``<word>`` with boundary markers."""
    w = f"<{word}>"
    out = []
    for n in range(min_n, max_n + 1):
        if n > len(w):
            continue
        out.extend(w[i:i + n] for i in range(len(w) - n + 1))
    return out


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _sg_subword_step(table, emb_out, feat_ids, feat_mask, target, negatives, lr):
    """Skip-gram NS step where the input vector is the mean of ``feat_ids``
    rows (word + its n-gram buckets). feat_ids: (B, F) into table,
    feat_mask: (B, F) 0/1, target: (B,), negatives: (B, K) into emb_out."""
    denom = jnp.maximum(feat_mask.sum(axis=1, keepdims=True), 1.0)
    v = jnp.einsum("bfd,bf->bd", jnp.take(table, feat_ids, axis=0), feat_mask) / denom
    u_pos = jnp.take(emb_out, target, axis=0)
    u_neg = jnp.take(emb_out, negatives, axis=0)
    pos_logit = jnp.sum(v * u_pos, axis=-1)
    neg_logit = jnp.einsum("bd,bkd->bk", v, u_neg)
    g_pos = jax.nn.sigmoid(pos_logit) - 1.0
    g_neg = jax.nn.sigmoid(neg_logit)
    grad_v = g_pos[:, None] * u_pos + jnp.einsum("bk,bkd->bd", g_neg, u_neg)
    loss = jnp.mean(-jax.nn.log_sigmoid(pos_logit)
                    - jnp.sum(jax.nn.log_sigmoid(-neg_logit), axis=-1))

    def mean_scatter(tbl, idx, grads, w=None):
        V = tbl.shape[0]
        wts = jnp.ones(idx.shape, grads.dtype) if w is None else w
        counts = jnp.zeros((V,), grads.dtype).at[idx.reshape(-1)].add(wts.reshape(-1))
        acc = jnp.zeros_like(tbl).at[idx.reshape(-1)].add(
            grads.reshape(-1, grads.shape[-1]) * wts.reshape(-1)[:, None])
        return tbl - lr * acc / jnp.maximum(counts, 1.0)[:, None]

    emb_out = mean_scatter(emb_out, target, g_pos[:, None] * v)
    emb_out = mean_scatter(emb_out, negatives, g_neg[..., None] * v[:, None, :])
    # each feature row receives grad_v / n_features(example)
    feat_grads = jnp.broadcast_to((grad_v / denom)[:, None, :],
                                  feat_ids.shape + (grad_v.shape[-1],))
    table = mean_scatter(table, feat_ids, feat_grads, w=feat_mask)
    return table, emb_out, loss


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def _supervised_step(table, W, b, feat_ids, feat_mask, labels, lr):
    """Mean-of-features → linear softmax; SGD on table, W, b."""
    def loss_fn(tbl, W_, b_):
        denom = jnp.maximum(feat_mask.sum(axis=1, keepdims=True), 1.0)
        v = jnp.einsum("bfd,bf->bd", jnp.take(tbl, feat_ids, axis=0), feat_mask) / denom
        logits = v @ W_ + b_
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.sum(labels * logp, axis=-1))

    loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1, 2))(table, W, b)
    return table - lr * grads[0], W - lr * grads[1], b - lr * grads[2], loss


class FastText:
    """Mirrors the reference builder surface::

        ft = FastText(supervised=False, dim=64, min_n=3, max_n=6, bucket=20000)
        ft.fit(sentences)                       # unsupervised skip-gram
        ft.get_word_vector("unseenword")        # works OOV via n-grams

        clf = FastText(supervised=True, dim=32)
        clf.fit(texts, labels)
        clf.predict("some text"); clf.predict_probability("some text")
    """

    def __init__(self, supervised: bool = False, dim: int = 100,
                 window_size: int = 5, min_word_frequency: int = 1,
                 min_n: int = 3, max_n: int = 6, bucket: int = 100_000,
                 negative: int = 5, epochs: int = 5, batch_size: int = 512,
                 learning_rate: float = 0.05, seed: int = 42,
                 max_features: int = 64, doc_max_features: int = 1024,
                 tokenizer_factory: Optional[TokenizerFactory] = None):
        self.supervised = supervised
        self.dim = dim
        self.window_size = window_size
        self.min_word_frequency = min_word_frequency
        self.min_n, self.max_n, self.bucket = min_n, max_n, bucket
        self.negative = negative
        self.epochs = epochs
        self.batch_size = batch_size
        self.learning_rate = learning_rate
        self.seed = seed
        self.max_features = max_features
        self.doc_max_features = doc_max_features
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.vocab: Optional[VocabCache] = None
        self.table: Optional[jax.Array] = None  # (V + bucket, dim)
        self.emb_out: Optional[jax.Array] = None
        self.W: Optional[jax.Array] = None
        self.b: Optional[jax.Array] = None
        self.labels_: List[str] = []
        self._modelmtype = "sup" if supervised else "skipgram"

    # ---- feature extraction ----
    def _ngram_ids(self, word: str) -> List[int]:
        V = len(self.vocab)
        return [V + (_fnv1a(g) % self.bucket)
                for g in char_ngrams(word, self.min_n, self.max_n)]

    def _word_feature_ids(self, word: str) -> List[int]:
        ids = []
        wi = self.vocab.index_of(word)
        if wi >= 0:
            ids.append(wi)
        ids.extend(self._ngram_ids(word))
        return ids or [len(self.vocab)]  # degenerate: first bucket row

    def _pad_features(self, feats: Sequence[Sequence[int]],
                      width: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
        F = width if width is not None else self.max_features
        ids = np.zeros((len(feats), F), np.int32)
        mask = np.zeros((len(feats), F), np.float32)
        for i, f in enumerate(feats):
            f = list(f)
            if len(f) > F:
                # even-stride subsample: keep whole-document coverage rather
                # than classifying by the opening tokens only
                f = [f[int(j * len(f) / F)] for j in range(F)]
            ids[i, :len(f)] = f
            mask[i, :len(f)] = 1.0
        return ids, mask

    def _tokens(self, texts: Iterable[str]) -> List[List[str]]:
        return [self.tokenizer_factory.create(t).get_tokens() for t in texts]

    # ---- training ----
    def fit(self, texts: Iterable[str], labels: Optional[Sequence[str]] = None
            ) -> "FastText":
        token_lists = self._tokens(texts)
        self.vocab = VocabCache(self.min_word_frequency).fit(token_lists)
        V, D = len(self.vocab), self.dim
        rng = np.random.default_rng(self.seed)
        self.table = jnp.asarray(
            rng.uniform(-0.5 / D, 0.5 / D, (V + self.bucket, D)).astype(np.float32))
        if self.supervised:
            if labels is None:
                raise ValueError("supervised FastText needs labels")
            return self._fit_supervised(token_lists, list(labels), rng)
        return self._fit_skipgram(token_lists, rng)

    def _fit_skipgram(self, token_lists, rng) -> "FastText":
        V, D = len(self.vocab), self.dim
        self.emb_out = jnp.zeros((V, D), jnp.float32)
        probs = self.vocab.negative_sampling_probs()
        # Precompute per-word subword feature lists once.
        feat_cache: Dict[int, List[int]] = {}
        for tl in token_lists:
            for w in tl:
                i = self.vocab.index_of(w)
                if i >= 0 and i not in feat_cache:
                    feat_cache[i] = [i] + self._ngram_ids(w)
        for epoch in range(self.epochs):
            lr = self.learning_rate * (1 - epoch / max(1, self.epochs))
            centers, targets = [], []
            for tl in token_lists:
                enc = [self.vocab.index_of(w) for w in tl]
                enc = [i for i in enc if i >= 0]
                for i, w in enumerate(enc):
                    win = rng.integers(1, self.window_size + 1)
                    for j in range(max(0, i - win), min(len(enc), i + win + 1)):
                        if j != i:
                            centers.append(w)
                            targets.append(enc[j])
            order = rng.permutation(len(centers))
            centers = np.asarray(centers, np.int32)[order]
            targets = np.asarray(targets, np.int32)[order]
            for s in range(0, len(centers), self.batch_size):
                sl = slice(s, s + self.batch_size)
                ids, mask = self._pad_features([feat_cache[c] for c in centers[sl]])
                negs = rng.choice(len(probs), size=(ids.shape[0], self.negative),
                                  p=probs).astype(np.int32)
                self.table, self.emb_out, _ = _sg_subword_step(
                    self.table, self.emb_out, jnp.asarray(ids), jnp.asarray(mask),
                    jnp.asarray(targets[sl]), jnp.asarray(negs), jnp.float32(lr))
        return self

    def _fit_supervised(self, token_lists, labels: List[str], rng) -> "FastText":
        self.labels_ = sorted(set(labels))
        lab_idx = {l: i for i, l in enumerate(self.labels_)}
        n_lab, D = len(self.labels_), self.dim
        self.W = jnp.zeros((D, n_lab), jnp.float32)
        self.b = jnp.zeros((n_lab,), jnp.float32)
        feats = [self._doc_feature_ids(tl) for tl in token_lists]
        y = np.eye(n_lab, dtype=np.float32)[[lab_idx[l] for l in labels]]
        for epoch in range(self.epochs):
            lr = self.learning_rate * (1 - epoch / max(1, self.epochs))
            order = rng.permutation(len(feats))
            for s in range(0, len(order), self.batch_size):
                sel = order[s:s + self.batch_size]
                ids, mask = self._pad_features([feats[i] for i in sel],
                                               width=self.doc_max_features)
                self.table, self.W, self.b, _ = _supervised_step(
                    self.table, self.W, self.b, jnp.asarray(ids),
                    jnp.asarray(mask), jnp.asarray(y[sel]), jnp.float32(lr))
        return self

    def _doc_feature_ids(self, tokens: List[str]) -> List[int]:
        ids: List[int] = []
        for w in tokens:
            ids.extend(self._word_feature_ids(w))
        return ids

    # ---- queries (reference FastText API names) ----
    def get_word_vector(self, word: str) -> np.ndarray:
        """Subword-composed vector; defined for OOV words too."""
        ids = self._word_feature_ids(word)
        return np.asarray(jnp.mean(jnp.take(self.table, jnp.asarray(ids), axis=0),
                                   axis=0))

    def similarity(self, w1: str, w2: str) -> float:
        a, b = self.get_word_vector(w1), self.get_word_vector(w2)
        return float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-12))

    def _predict_logits(self, text: str) -> np.ndarray:
        ids, mask = self._pad_features(
            [self._doc_feature_ids(self.tokenizer_factory.create(text).get_tokens())],
            width=self.doc_max_features)
        denom = max(mask.sum(), 1.0)
        v = (np.asarray(self.table)[ids[0]] * mask[0][:, None]).sum(0) / denom
        return v @ np.asarray(self.W) + np.asarray(self.b)

    def predict(self, text: str) -> str:
        return self.labels_[int(np.argmax(self._predict_logits(text)))]

    def predict_probability(self, text: str) -> Dict[str, float]:
        logits = self._predict_logits(text)
        p = np.exp(logits - logits.max())
        p /= p.sum()
        return dict(zip(self.labels_, p.tolist()))

    def word_vectors_for(self, words: Sequence[str]) -> np.ndarray:
        return np.stack([self.get_word_vector(w) for w in words])


    # ---- persistence (reference FastText model save/load) ----
    def save(self, path: str) -> None:
        """Save the full model (config + vocab incl. counts + tables) to one
        .npz. The tokenizer factory is NOT serialized (it may be arbitrary
        code) — pass the same one to :meth:`load`."""
        import json
        if self.vocab is None or self.table is None:
            raise ValueError("fit() before save()")
        cfg = dict(supervised=self.supervised, dim=self.dim,
                   window_size=self.window_size,
                   min_word_frequency=self.min_word_frequency,
                   min_n=self.min_n, max_n=self.max_n, bucket=self.bucket,
                   negative=self.negative, epochs=self.epochs,
                   batch_size=self.batch_size, learning_rate=self.learning_rate,
                   seed=self.seed, max_features=self.max_features,
                   doc_max_features=self.doc_max_features)
        words = [self.vocab.word_at_index(i) for i in range(len(self.vocab))]
        meta = dict(config=cfg, labels=self.labels_, words=words,
                    counts={w: int(c) for w, c in self.vocab.counts.items()})
        arrays = {"meta": np.frombuffer(json.dumps(meta).encode(), np.uint8),
                  "table": np.asarray(self.table)}
        if self.emb_out is not None:
            arrays["emb_out"] = np.asarray(self.emb_out)
        if self.W is not None:
            arrays["W"] = np.asarray(self.W)
            arrays["b"] = np.asarray(self.b)
        np.savez(path if path.endswith(".npz") else path + ".npz", **arrays)

    @classmethod
    def load(cls, path: str,
             tokenizer_factory: Optional[TokenizerFactory] = None) -> "FastText":
        """Load a saved model. Supply the SAME ``tokenizer_factory`` used at
        training time if it was customized."""
        import json
        with np.load(path if path.endswith(".npz") else path + ".npz") as data:
            meta = json.loads(bytes(data["meta"]).decode())
            table = jnp.asarray(data["table"])
            emb_out = jnp.asarray(data["emb_out"]) if "emb_out" in data else None
            W = jnp.asarray(data["W"]) if "W" in data else None
            b = jnp.asarray(data["b"]) if "b" in data else None
        ft = cls(tokenizer_factory=tokenizer_factory, **meta["config"])
        ft.vocab = VocabCache.restore(meta["words"], meta["counts"],
                                      ft.min_word_frequency)
        ft.table, ft.emb_out, ft.W, ft.b = table, emb_out, W, b
        ft.labels_ = list(meta["labels"])
        return ft
