"""NLP: word embeddings + text pipeline.

Rebuild of the reference's deeplearning4j-nlp (upstream
``org.deeplearning4j.models.word2vec`` etc.): Word2Vec (skip-gram & CBOW with
negative sampling — the hot loops that are native nd4j ops ``SkipGram``/
``CBOW`` in the reference run here as one jitted minibatch update),
ParagraphVectors (PV-DBOW), tokenizer SPI, vocab cache,
``WordVectorSerializer``.
"""

from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory, TokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabCache
from deeplearning4j_tpu.nlp.word2vec import ParagraphVectors, Word2Vec
from deeplearning4j_tpu.nlp.glove import Glove
from deeplearning4j_tpu.nlp.fasttext import FastText
from deeplearning4j_tpu.nlp.serializer import WordVectorSerializer

__all__ = ["Word2Vec", "ParagraphVectors", "Glove", "FastText", "VocabCache",
           "TokenizerFactory", "DefaultTokenizerFactory", "WordVectorSerializer"]
