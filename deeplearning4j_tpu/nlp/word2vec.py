"""Word2Vec / ParagraphVectors.

Rebuild of upstream ``org.deeplearning4j.models.word2vec.Word2Vec`` and
``ParagraphVectors``. The reference runs skip-gram/CBOW inner loops as native
nd4j ops (``SkipGram``/``CBOW`` custom ops); here the whole minibatch update
— embedding gathers, negative-sampling logits, gradients, scatter-update —
is ONE jitted program with donated embedding tables. Pair generation
(windowing, subsampling, negative draws) stays on host numpy, overlapped
with device steps.

Training objective: skip-gram (or CBOW) with negative sampling:
  L = -log σ(u_ctx · v_in) - Σ_k log σ(-u_negk · v_in)
"""

from __future__ import annotations

import functools
from typing import Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory, TokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabCache


def _ns_step_impl(emb_in, emb_out, center, context, negatives, lr, cbow=False):
    """One negative-sampling SGD minibatch.

    emb_in:  (V, D) input vectors   emb_out: (V, D) output vectors
    center:  (B,) int32 — skip-gram: input word; CBOW: target word
    context: (B, C) int32 — skip-gram: C=1 context; CBOW: window words
    negatives: (B, K) int32
    """
    if cbow:
        v = jnp.mean(jnp.take(emb_in, context, axis=0), axis=1)  # (B, D)
        tgt = center
    else:
        v = jnp.take(emb_in, center, axis=0)
        tgt = context[:, 0]
    u_pos = jnp.take(emb_out, tgt, axis=0)  # (B, D)
    u_neg = jnp.take(emb_out, negatives, axis=0)  # (B, K, D)

    pos_logit = jnp.sum(v * u_pos, axis=-1)
    neg_logit = jnp.einsum("bd,bkd->bk", v, u_neg)
    # gradients of -logσ(pos) - Σ logσ(-neg)
    g_pos = jax.nn.sigmoid(pos_logit) - 1.0            # (B,)
    g_neg = jax.nn.sigmoid(neg_logit)                   # (B, K)
    grad_v = g_pos[:, None] * u_pos + jnp.einsum("bk,bkd->bd", g_neg, u_neg)
    grad_u_pos = g_pos[:, None] * v
    grad_u_neg = g_neg[..., None] * v[:, None, :]

    loss = jnp.mean(-jax.nn.log_sigmoid(pos_logit)
                    - jnp.sum(jax.nn.log_sigmoid(-neg_logit), axis=-1))

    def mean_scatter(table, idx, grads):
        """Per-row MEAN of duplicate-index gradients. The sequential
        reference updates each occurrence against fresh values, which is
        self-limiting; a summed scatter multiplies the step of frequent
        words by their batch count and diverges."""
        V = table.shape[0]
        counts = jnp.zeros((V,), grads.dtype).at[idx].add(1.0)
        acc = jnp.zeros_like(table).at[idx].add(grads)
        return table - lr * acc / jnp.maximum(counts, 1.0)[:, None]

    emb_out = mean_scatter(emb_out, tgt, grad_u_pos)
    emb_out = mean_scatter(emb_out, negatives.reshape(-1),
                           grad_u_neg.reshape(-1, grad_u_neg.shape[-1]))
    if cbow:
        c = context.shape[1]
        emb_in = mean_scatter(emb_in, context.reshape(-1),
                              jnp.repeat(grad_v / c, c, axis=0))
    else:
        emb_in = mean_scatter(emb_in, center, grad_v)
    return emb_in, emb_out, loss


_ns_step = functools.partial(jax.jit, donate_argnums=(0, 1),
                             static_argnames=("cbow",))(_ns_step_impl)


@functools.partial(jax.jit, donate_argnums=(0, 1), static_argnames=("cbow",))
def _ns_step_group(emb_in, emb_out, centers, contexts, negatives, lr,
                   cbow=False):
    """G sequential minibatches as ONE device dispatch (lax.fori_loop over
    the stacked leading axis) — table math identical to calling
    ``_ns_step`` G times, minus G-1 host round trips. The per-step form
    measures ~5 ms/step through the remote tunnel with a ~2-3 ms device
    step, i.e. dispatch-bound; grouping is the same medicine as
    ``Environment.dispatch_unroll`` in the nn fit loops. Inputs are
    (G, B)/(G, B, C)/(G, B, K); returns the last step's loss."""
    def body(i, carry):
        ei, eo, _ = carry
        return _ns_step_impl(ei, eo, centers[i], contexts[i], negatives[i],
                             lr, cbow=cbow)
    return jax.lax.fori_loop(
        0, centers.shape[0], body,
        (emb_in, emb_out, jnp.float32(0.0)))


class Word2Vec:
    """Builder mirrors the reference::

        w2v = (Word2Vec.builder()
               .layer_size(100).window_size(5).min_word_frequency(5)
               .negative(5).iterations(1).epochs(1).seed(42)
               .learning_rate(0.025).elements_learning_algorithm("skipgram")
               .build())
        w2v.fit(sentences)          # iterable of strings
        w2v.get_word_vector("day"); w2v.words_nearest("day", 5)
    """

    def __init__(self, layer_size=100, window_size=5, min_word_frequency=5,
                 negative=5, epochs=1, iterations=1, batch_size=512,
                 learning_rate=0.025, min_learning_rate=1e-4, seed=42,
                 subsample=1e-3, algorithm="skipgram",
                 tokenizer_factory: Optional[TokenizerFactory] = None):
        self.layer_size = layer_size
        self.window_size = window_size
        self.min_word_frequency = min_word_frequency
        self.negative = negative
        self.epochs = epochs
        self.iterations = iterations
        self.batch_size = batch_size
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.seed = seed
        self.subsample = subsample
        self.algorithm = algorithm
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.vocab: Optional[VocabCache] = None
        self.emb_in: Optional[jax.Array] = None
        self.emb_out: Optional[jax.Array] = None

    # -- builder --
    class Builder:
        def __init__(self):
            self._kw = {}

        def __getattr__(self, key):
            def setter(value):
                self._kw[{"elements_learning_algorithm": "algorithm"}.get(key, key)] = value
                return self
            return setter

        def build(self) -> "Word2Vec":
            return Word2Vec(**self._kw)

    @staticmethod
    def builder() -> "Word2Vec.Builder":
        return Word2Vec.Builder()

    # -- training --
    def _sentences_tokens(self, sentences: Iterable[str]) -> List[List[str]]:
        return [self.tokenizer_factory.create(s).get_tokens() for s in sentences]

    def fit(self, sentences: Iterable[str]) -> "Word2Vec":
        token_lists = self._sentences_tokens(sentences)
        self.vocab = VocabCache(self.min_word_frequency).fit(token_lists)
        V, D = len(self.vocab), self.layer_size
        rng = np.random.default_rng(self.seed)
        self.emb_in = jnp.asarray(
            rng.uniform(-0.5 / D, 0.5 / D, (V, D)).astype(np.float32))
        self.emb_out = jnp.asarray(np.zeros((V, D), np.float32))
        probs = self.vocab.negative_sampling_probs()
        encoded = [self.vocab.encode(t) for t in token_lists]
        cbow = self.algorithm.lower() == "cbow"
        total_steps = max(1, self.epochs * self.iterations)
        from deeplearning4j_tpu.runtime.environment import get_environment
        from deeplearning4j_tpu.runtime.state_packing import GroupedDispatch
        unroll = max(1, get_environment().dispatch_unroll)
        lr_box = [jnp.float32(self.learning_rate)]

        def run_single(a):
            c_, x_, n_ = a
            self.emb_in, self.emb_out, loss = _ns_step(
                self.emb_in, self.emb_out, jnp.asarray(c_), jnp.asarray(x_),
                jnp.asarray(n_), lr_box[0], cbow=cbow)
            return loss

        def run_group(todo):
            # consecutive same-shape batches as ONE dispatch
            # (env.dispatch_unroll, same protocol as the nn fit loops;
            # GroupedDispatch runs partial tails singly so only ONE
            # grouped shape ever compiles)
            self.emb_in, self.emb_out, loss = _ns_step_group(
                self.emb_in, self.emb_out,
                jnp.asarray(np.stack([b[0] for b in todo])),
                jnp.asarray(np.stack([b[1] for b in todo])),
                jnp.asarray(np.stack([b[2] for b in todo])),
                lr_box[0], cbow=cbow)
            return [loss] * len(todo)

        gd = GroupedDispatch(
            unroll=unroll,
            compatible=lambda a, b: a[0].shape == b[0].shape,
            run_single=run_single, run_group=run_group,
            deliver=lambda args, loss: None)
        try:
            for epoch in range(self.epochs):
                lr_box[0] = jnp.float32(max(
                    self.min_learning_rate,
                    self.learning_rate * (1 - epoch / max(1, self.epochs))))
                for _ in range(self.iterations):
                    pairs = self._make_pairs(encoded, rng, cbow)
                    for i in range(0, len(pairs[0]), self.batch_size):
                        sl = slice(i, i + self.batch_size)
                        center, context = pairs[0][sl], pairs[1][sl]
                        negs = rng.choice(
                            len(probs),
                            size=(context.shape[0], self.negative),
                            p=probs).astype(np.int32)
                        gd.submit((center, context, negs))
                    gd.flush()  # epoch boundary: lr changes next epoch
        finally:
            gd.drain_on_error()
        return self

    def _make_pairs(self, encoded: List[List[int]], rng, cbow: bool):
        centers, contexts = [], []
        C = self.window_size
        for sent in encoded:
            n = len(sent)
            for i, w in enumerate(sent):
                win = rng.integers(1, C + 1)
                ctx = [sent[j] for j in range(max(0, i - win), min(n, i + win + 1))
                       if j != i]
                if not ctx:
                    continue
                if cbow:
                    ctx = (ctx * C)[:C]  # pad by repetition to fixed width
                    centers.append(w)
                    contexts.append(ctx)
                else:
                    for c in ctx:
                        centers.append(w)
                        contexts.append([c])
        order = rng.permutation(len(centers))
        return (np.asarray(centers, np.int32)[order],
                np.asarray(contexts, np.int32)[order])

    # -- queries (reference WordVectors API) --
    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.index_of(word)
        return None if i < 0 else np.asarray(self.emb_in[i])

    def has_word(self, word: str) -> bool:
        return self.vocab is not None and self.vocab.contains_word(word)

    def similarity(self, w1: str, w2: str) -> float:
        a, b = self.get_word_vector(w1), self.get_word_vector(w2)
        if a is None or b is None:
            return float("nan")
        return float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-12))

    def words_nearest(self, word: str, n: int = 10) -> List[str]:
        i = self.vocab.index_of(word)
        if i < 0:
            return []
        emb = np.asarray(self.emb_in)
        v = emb[i] / (np.linalg.norm(emb[i]) + 1e-12)
        norms = emb / (np.linalg.norm(emb, axis=1, keepdims=True) + 1e-12)
        sims = norms @ v
        order = np.argsort(-sims)
        return [self.vocab.word_at_index(j) for j in order if j != i][:n]

    def save(self, path: str) -> None:
        from deeplearning4j_tpu.nlp.serializer import WordVectorSerializer
        WordVectorSerializer.write_word_vectors(self, path)


class ParagraphVectors(Word2Vec):
    """PV-DBOW (reference ``ParagraphVectors``): a document vector is trained
    to predict the words it contains (skip-gram with the doc id as input)."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.doc_vectors: Optional[jax.Array] = None
        self._n_docs = 0

    def fit(self, documents: Iterable[str]) -> "ParagraphVectors":
        token_lists = self._sentences_tokens(documents)
        self.vocab = VocabCache(self.min_word_frequency).fit(token_lists)
        V, D = len(self.vocab), self.layer_size
        self._n_docs = len(token_lists)
        rng = np.random.default_rng(self.seed)
        self.doc_vectors = jnp.asarray(
            rng.uniform(-0.5 / D, 0.5 / D, (self._n_docs, D)).astype(np.float32))
        self.emb_out = jnp.asarray(np.zeros((V, D), np.float32))
        self.emb_in = self.doc_vectors  # alias: docs are the "input words"
        probs = self.vocab.negative_sampling_probs()
        for epoch in range(self.epochs):
            lr = max(self.min_learning_rate,
                     self.learning_rate * (1 - epoch / max(1, self.epochs)))
            centers, contexts = [], []
            for d, toks in enumerate(token_lists):
                for w in self.vocab.encode(toks):
                    centers.append(d)
                    contexts.append([w])
            order = rng.permutation(len(centers))
            centers = np.asarray(centers, np.int32)[order]
            contexts = np.asarray(contexts, np.int32)[order]
            for i in range(0, len(centers), self.batch_size):
                sl = slice(i, i + self.batch_size)
                negs = jnp.asarray(rng.choice(
                    len(probs), size=(len(centers[sl]), self.negative), p=probs)
                    .astype(np.int32))
                self.doc_vectors, self.emb_out, _ = _ns_step(
                    self.doc_vectors, self.emb_out, jnp.asarray(centers[sl]),
                    jnp.asarray(contexts[sl]), negs, jnp.float32(lr), cbow=False)
        self.emb_in = self.doc_vectors
        return self

    def get_doc_vector(self, i: int) -> np.ndarray:
        return np.asarray(self.doc_vectors[i])

    def docs_nearest(self, i: int, n: int = 10) -> List[int]:
        emb = np.asarray(self.doc_vectors)
        v = emb[i] / (np.linalg.norm(emb[i]) + 1e-12)
        sims = (emb / (np.linalg.norm(emb, axis=1, keepdims=True) + 1e-12)) @ v
        return [int(j) for j in np.argsort(-sims) if j != i][:n]
