"""Tokenizer SPI (reference
``org.deeplearning4j.text.tokenization.tokenizerfactory``)."""

from __future__ import annotations

import re
from typing import Callable, List, Optional


class TokenPreProcess:
    """Reference ``CommonPreprocessor``: lowercase + strip punctuation."""

    _PUNCT = re.compile(r"[\.,!?;:\"'\(\)\[\]{}<>]")

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token.lower())


class Tokenizer:
    def __init__(self, tokens: List[str]):
        self._tokens = tokens

    def get_tokens(self) -> List[str]:
        return list(self._tokens)

    def count_tokens(self) -> int:
        return len(self._tokens)


class TokenizerFactory:
    def create(self, text: str) -> Tokenizer:
        raise NotImplementedError

    def set_token_pre_processor(self, p: TokenPreProcess) -> None:
        self._pre = p


class DefaultTokenizerFactory(TokenizerFactory):
    """Whitespace/word tokenizer (reference ``DefaultTokenizerFactory``)."""

    _WORD = re.compile(r"\S+")

    def __init__(self):
        self._pre: Optional[TokenPreProcess] = None

    def create(self, text: str) -> Tokenizer:
        toks = self._WORD.findall(text)
        if self._pre is not None:
            toks = [self._pre.pre_process(t) for t in toks]
        return Tokenizer([t for t in toks if t])


class NGramTokenizerFactory(TokenizerFactory):
    def __init__(self, n_min: int = 1, n_max: int = 2):
        self.n_min, self.n_max = n_min, n_max
        self._base = DefaultTokenizerFactory()
        self._pre = None

    def create(self, text: str) -> Tokenizer:
        base = self._base.create(text).get_tokens()
        if self._pre:
            base = [self._pre.pre_process(t) for t in base]
        out = []
        for n in range(self.n_min, self.n_max + 1):
            for i in range(len(base) - n + 1):
                out.append(" ".join(base[i:i + n]))
        return Tokenizer(out)
