"""GloVe embeddings (reference ``org.deeplearning4j.models.glove.Glove``).

Co-occurrence statistics are accumulated on host (sparse dict over window
pairs with 1/distance weighting, as in GloVe); training minimises
``f(X_ij) (w_i·w~_j + b_i + b~_j - log X_ij)^2`` with AdaGrad, where the
whole COO minibatch update runs as one jitted donated program.
"""

from __future__ import annotations

import functools
from collections import defaultdict
from typing import Iterable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory, TokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabCache


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7))
def _glove_step(w, wt, b, bt, gw, gwt, gb, gbt, rows, cols, logx, weight, lr):
    wi = jnp.take(w, rows, axis=0)
    wj = jnp.take(wt, cols, axis=0)
    bi = jnp.take(b, rows)
    bj = jnp.take(bt, cols)
    diff = jnp.sum(wi * wj, axis=-1) + bi + bj - logx
    fdiff = weight * diff
    loss = jnp.mean(fdiff * diff)

    g_wi = fdiff[:, None] * wj
    g_wj = fdiff[:, None] * wi
    g_b = fdiff

    def adagrad_update(table, gtable, idx, grads):
        acc = jnp.zeros_like(gtable).at[idx].add(grads * grads)
        gtable = gtable + acc
        denom = jnp.sqrt(jnp.take(gtable, idx, axis=0)) + 1e-8
        upd = jnp.zeros_like(table).at[idx].add(grads / denom)
        return table - lr * upd, gtable

    w, gw = adagrad_update(w, gw, rows, g_wi)
    wt, gwt = adagrad_update(wt, gwt, cols, g_wj)
    b2, gb = adagrad_update(b[:, None], gb[:, None], rows, g_b[:, None])
    bt2, gbt = adagrad_update(bt[:, None], gbt[:, None], cols, g_b[:, None])
    return w, wt, b2[:, 0], bt2[:, 0], gw, gwt, gb[:, 0], gbt[:, 0], loss


class Glove:
    def __init__(self, layer_size: int = 100, window_size: int = 5,
                 min_word_frequency: int = 1, epochs: int = 25,
                 learning_rate: float = 0.05, x_max: float = 100.0,
                 alpha: float = 0.75, batch_size: int = 4096, seed: int = 42,
                 tokenizer_factory: Optional[TokenizerFactory] = None):
        self.layer_size = layer_size
        self.window_size = window_size
        self.min_word_frequency = min_word_frequency
        self.epochs = epochs
        self.learning_rate = learning_rate
        self.x_max = x_max
        self.alpha = alpha
        self.batch_size = batch_size
        self.seed = seed
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.vocab: Optional[VocabCache] = None
        self.emb: Optional[np.ndarray] = None

    def fit(self, sentences: Iterable[str]) -> "Glove":
        token_lists = [self.tokenizer_factory.create(s).get_tokens() for s in sentences]
        self.vocab = VocabCache(self.min_word_frequency).fit(token_lists)
        cooc = defaultdict(float)
        for toks in token_lists:
            ids = self.vocab.encode(toks)
            for i, wi in enumerate(ids):
                for j in range(max(0, i - self.window_size), i):
                    cooc[(wi, ids[j])] += 1.0 / (i - j)
                    cooc[(ids[j], wi)] += 1.0 / (i - j)
        rows = np.asarray([k[0] for k in cooc], np.int32)
        cols = np.asarray([k[1] for k in cooc], np.int32)
        vals = np.asarray(list(cooc.values()), np.float32)
        logx = np.log(vals)
        weight = np.minimum((vals / self.x_max) ** self.alpha, 1.0).astype(np.float32)

        V, D = len(self.vocab), self.layer_size
        rng = np.random.default_rng(self.seed)
        w = jnp.asarray(rng.uniform(-0.5 / D, 0.5 / D, (V, D)).astype(np.float32))
        wt = jnp.asarray(rng.uniform(-0.5 / D, 0.5 / D, (V, D)).astype(np.float32))
        b = jnp.zeros((V,), jnp.float32)
        bt = jnp.zeros((V,), jnp.float32)
        gw = jnp.full((V, D), 1e-8, jnp.float32)
        gwt = jnp.full((V, D), 1e-8, jnp.float32)
        gb = jnp.full((V,), 1e-8, jnp.float32)
        gbt = jnp.full((V,), 1e-8, jnp.float32)
        n = len(rows)
        for _ in range(self.epochs):
            order = rng.permutation(n)
            for s in range(0, n, self.batch_size):
                idx = order[s:s + self.batch_size]
                (w, wt, b, bt, gw, gwt, gb, gbt, _) = _glove_step(
                    w, wt, b, bt, gw, gwt, gb, gbt,
                    jnp.asarray(rows[idx]), jnp.asarray(cols[idx]),
                    jnp.asarray(logx[idx]), jnp.asarray(weight[idx]),
                    jnp.float32(self.learning_rate))
        self.emb = np.asarray(w) + np.asarray(wt)  # GloVe: sum of both tables
        return self

    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.index_of(word)
        return None if i < 0 else self.emb[i]

    def similarity(self, w1: str, w2: str) -> float:
        a, b = self.get_word_vector(w1), self.get_word_vector(w2)
        if a is None or b is None:
            return float("nan")
        return float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-12))

    def words_nearest(self, word: str, n: int = 10) -> List[str]:
        i = self.vocab.index_of(word)
        if i < 0:
            return []
        norms = self.emb / (np.linalg.norm(self.emb, axis=1, keepdims=True) + 1e-12)
        sims = norms @ norms[i]
        return [self.vocab.word_at_index(j) for j in np.argsort(-sims) if j != i][:n]
