"""Word-vector serialization (reference
``org.deeplearning4j.models.embeddings.loader.WordVectorSerializer``):
classic word2vec text format (one line per word: token + floats) read/write,
so vectors interchange with gensim/word2vec tooling."""

from __future__ import annotations

import numpy as np


class WordVectorSerializer:
    @staticmethod
    def write_word_vectors(w2v, path: str) -> None:
        emb = np.asarray(w2v.emb_in)
        with open(path, "w") as f:
            f.write(f"{emb.shape[0]} {emb.shape[1]}\n")
            for i in range(emb.shape[0]):
                word = w2v.vocab.word_at_index(i)
                vec = " ".join(f"{x:.6f}" for x in emb[i])
                f.write(f"{word} {vec}\n")

    @staticmethod
    def read_word_vectors(path: str):
        """Returns (vocab_list, matrix)."""
        with open(path) as f:
            header = f.readline().split()
            n, d = int(header[0]), int(header[1])
            words, rows = [], np.empty((n, d), np.float32)
            for i in range(n):
                parts = f.readline().rstrip("\n").split(" ")
                words.append(parts[0])
                rows[i] = [float(x) for x in parts[1:d + 1]]
        return words, rows

    @staticmethod
    def load_txt(path: str):
        """Reference ``loadTxt``: returns a queryable Word2Vec-like object."""
        from deeplearning4j_tpu.nlp.vocab import VocabCache
        from deeplearning4j_tpu.nlp.word2vec import Word2Vec
        import jax.numpy as jnp
        words, rows = WordVectorSerializer.read_word_vectors(path)
        w2v = Word2Vec(layer_size=rows.shape[1], min_word_frequency=1)
        w2v.vocab = VocabCache.restore(words, {w: 1 for w in words}, 1)
        w2v.emb_in = jnp.asarray(rows)
        w2v.emb_out = jnp.zeros_like(w2v.emb_in)
        return w2v
