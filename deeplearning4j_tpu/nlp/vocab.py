"""Vocabulary cache (reference
``org.deeplearning4j.models.word2vec.wordstore.inmemory.AbstractCache``):
word -> index, frequency counts, min-frequency pruning, and the unigram^0.75
negative-sampling table."""

from __future__ import annotations

from collections import Counter
from typing import Dict, Iterable, List, Optional

import numpy as np


class VocabCache:
    def __init__(self, min_word_frequency: int = 1):
        self.min_word_frequency = min_word_frequency
        self.word2idx: Dict[str, int] = {}
        self.idx2word: List[str] = []
        self.counts: Counter = Counter()
        self._sampling_probs: Optional[np.ndarray] = None

    def fit(self, token_stream: Iterable[List[str]]) -> "VocabCache":
        for tokens in token_stream:
            self.counts.update(tokens)
        for w, c in self.counts.most_common():
            if c >= self.min_word_frequency:
                self.word2idx[w] = len(self.idx2word)
                self.idx2word.append(w)
        return self

    @classmethod
    def restore(cls, words: List[str], counts: Dict[str, int],
                min_word_frequency: int = 1) -> "VocabCache":
        """Rebuild a cache with an EXACT retained-word index order (``words``)
        and the full frequency table (``counts`` may include words below
        ``min_word_frequency`` that were pruned from the index). Used by model
        deserialization — refitting would reorder ties and drop count-1 words'
        frequencies."""
        vocab = cls(min_word_frequency)
        vocab.counts.update(counts)
        for i, w in enumerate(words):
            vocab.word2idx[w] = i
            vocab.idx2word.append(w)
        return vocab

    def __len__(self) -> int:
        return len(self.idx2word)

    def num_words(self) -> int:
        return len(self.idx2word)

    def contains_word(self, w: str) -> bool:
        return w in self.word2idx

    def index_of(self, w: str) -> int:
        return self.word2idx.get(w, -1)

    def word_at_index(self, i: int) -> str:
        return self.idx2word[i]

    def word_frequency(self, w: str) -> int:
        return self.counts.get(w, 0)

    def encode(self, tokens: List[str]) -> List[int]:
        return [self.word2idx[t] for t in tokens if t in self.word2idx]

    def negative_sampling_probs(self) -> np.ndarray:
        """Unigram^0.75 distribution (word2vec's negative-sampling table)."""
        if self._sampling_probs is None:
            freqs = np.asarray([self.counts[w] for w in self.idx2word], np.float64)
            p = freqs ** 0.75
            self._sampling_probs = (p / p.sum()).astype(np.float64)
        return self._sampling_probs
