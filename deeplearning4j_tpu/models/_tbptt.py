"""Shared truncated-BPTT helpers for MultiLayerNetwork and ComputationGraph.

One implementation of the chunking rules so the two network classes cannot
drift: what counts as a sequence array, how a time window is sliced, and
which dtype recurrent carries start in.
"""

from __future__ import annotations

import jax.numpy as jnp


def is_sequence_array(v) -> bool:
    """(B, T, F) float features OR (B, T) integer token ids."""
    if not hasattr(v, "ndim"):
        return False
    return v.ndim == 3 or (v.ndim == 2 and jnp.issubdtype(v.dtype, jnp.integer))


def seq_length(v) -> int:
    return v.shape[1]


def slice_time(v, t0: int, length: int):
    """Window [t0, t0+length) of a sequence array; non-sequence arrays pass
    through unchanged."""
    if is_sequence_array(v):
        return v[:, t0:t0 + length]
    return v


def carry_dtype(sample, compute_dtype):
    """Recurrent carries start in the input dtype when it is floating (so
    bf16 stays bf16 through the scan), else the environment compute dtype."""
    dt = getattr(sample, "dtype", None)
    if dt is not None and jnp.issubdtype(dt, jnp.floating):
        return dt
    return compute_dtype
