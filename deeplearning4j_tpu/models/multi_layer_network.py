"""MultiLayerNetwork: linear layer stack with a fully-jitted training engine.

Rebuild of upstream ``org.deeplearning4j.nn.multilayer.MultiLayerNetwork``.
API parity: ``init``, ``fit(iterator)``, ``output``, ``score``, ``evaluate``,
``params``, ``set_listeners``, ``rnn_time_step`` / ``rnn_clear_previous_state``
(stateful inference), truncated BPTT, transfer-learning freeze support.

TPU-first re-architecture (NOT a port — SURVEY.md §7.1):

- The reference dispatches one JNI call per op per layer per step; here the
  ENTIRE step (forward, loss, backward via ``jax.grad``, updater, param
  update) is one XLA program, compiled once, with the state pytree donated —
  the analog of the reference's flat-params buffer reused in place.
- The reference's hand-written ``backpropGradient`` per layer does not exist:
  autodiff of the composed forward provides it.
- Updater state lives next to params in :class:`TrainState` (reference:
  ``UpdaterBlock`` flat views), so checkpoints capture exact resume state.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeplearning4j_tpu.nn.base import GlobalConfig, Layer
from deeplearning4j_tpu.nn.config import MultiLayerConfiguration
from deeplearning4j_tpu.nn.core_layers import LossLayer, OutputLayer
from deeplearning4j_tpu.models._tbptt import (carry_dtype, is_sequence_array,
                                               slice_time)
from deeplearning4j_tpu.nn.recurrent_layers import BaseRecurrentLayer
from deeplearning4j_tpu.runtime.environment import get_environment
from deeplearning4j_tpu.runtime.rng import RngManager
from deeplearning4j_tpu.train.listeners import PerformanceListener, TrainingListener
from deeplearning4j_tpu.train.updaters import Sgd, Updater, gradient_normalization_transform


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    """Donated training state: one pytree through the jitted step."""

    params: Dict[str, Dict[str, jax.Array]]
    model_state: Dict[str, Dict[str, jax.Array]]
    opt_state: Any
    step: jax.Array  # scalar int32


def _layer_key(i: int, layer: Layer) -> str:
    return layer.name or f"layer_{i}"


def _group_compatible(a, b) -> bool:
    """Whether two buffered (x, y, rng, fm, lm) step tuples may share one
    unrolled dispatch: same input/label shapes and mask presence."""
    return (a[0].shape == b[0].shape and a[1].shape == b[1].shape
            and (a[3] is None) == (b[3] is None)
            and (a[4] is None) == (b[4] is None))


class MultiLayerNetwork:
    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layers: List[Layer] = conf.layers
        for l in self.layers:
            l._g = conf.global_conf
        self.rng = RngManager(conf.global_conf.seed)
        self.train_state: Optional[TrainState] = None
        self._listeners: List[TrainingListener] = []
        self._iteration = 0
        self._epoch = 0
        self._score = float("nan")
        self._rnn_carries: Optional[Dict[str, Any]] = None
        self._tx: Optional[optax.GradientTransformation] = None
        self._jit_cache: Dict[str, Any] = {}

    # ------------------------------------------------------------------ init
    def init(self, params: Optional[Dict] = None) -> "MultiLayerNetwork":
        """Initialise parameters and optimizer state (reference ``init()``)."""
        g = self.conf.global_conf
        if g.dtype is None:
            g = dataclasses.replace(g, dtype=get_environment().default_dtype)
        def init_all(key):
            # one jitted program for ALL param draws — per-param eager init
            # would emit hundreds of tiny kernels (slow under remote compile)
            ps: Dict[str, Dict] = {}
            ss: Dict[str, Dict] = {}
            for i, layer in enumerate(self.layers):
                it = self.conf.layer_input_types[i] if self.conf.layer_input_types else None
                p, s = layer.init(jax.random.fold_in(key, i), it, g)
                k = _layer_key(i, layer)
                if p:
                    ps[k] = p
                if s:
                    ss[k] = s
            return ps, ss

        new_params, model_state = jax.jit(init_all)(jax.random.PRNGKey(g.seed))
        if params is not None:
            new_params = params
        self._tx = self._build_tx(new_params)
        trainable = self._trainable(new_params)
        opt_state = self._tx.init(trainable)
        self.train_state = TrainState(
            params=new_params, model_state=model_state, opt_state=opt_state,
            step=jnp.zeros((), jnp.int32))
        self._jit_cache.clear()
        return self

    def _trainable(self, params):
        # Frozen layers keep params but receive zero updates (handled by labels)
        return params

    def _layer_transform(self, layer) -> optax.GradientTransformation:
        """The optax transform one layer's params train under — shared by
        the standard per-layer-key multi_transform and the pipe executor's
        stage-stacked trunk (``parallel/plan_exec.py``), so packed and
        unpacked updates are the same math."""
        g = self.conf.global_conf
        default_updater: Updater = g.updater if g.updater is not None else Sgd(0.1)
        if layer.frozen:
            return optax.set_to_zero()
        upd = layer.updater if layer.updater is not None else default_updater
        chain = []
        gn = gradient_normalization_transform(
            g.gradient_normalization, g.gradient_normalization_threshold)
        if gn is not None:
            chain.append(gn)
        chain.append(upd.make())
        wd = layer.weight_decay if layer.weight_decay is not None else g.weight_decay
        if wd:
            # Decoupled decay AFTER the updater, scaled by the LR (the
            # reference's WeightDecay with applyLR=true; AdamW-style).
            from deeplearning4j_tpu.train.updaters import decoupled_weight_decay
            reg_keys = set(layer.regularizable_params())
            chain.append(decoupled_weight_decay(
                wd, upd._lr(), mask=lambda p, rk=reg_keys: _mask_keys(p, rk)))
        return optax.chain(*chain) if len(chain) > 1 else chain[0]

    def _build_tx(self, params) -> optax.GradientTransformation:
        transforms: Dict[str, optax.GradientTransformation] = {}
        labels = {}
        for i, layer in enumerate(self.layers):
            k = _layer_key(i, layer)
            if k not in params:
                continue
            transforms[k] = self._layer_transform(layer)
            labels[k] = jax.tree.map(lambda _: k, params[k])
        return optax.multi_transform(transforms, labels)

    # --------------------------------------------------------------- forward
    def _forward(self, params, model_state, x, *, training: bool, rng,
                 fmask=None, carries: Optional[Dict] = None):
        """Compose all layers; returns (final_out, pre_output_input, new_state,
        new_carries). ``pre_output_input`` is the input fed to the final
        (output) layer — AFTER that layer's input dropout, so the fused loss
        path and the forward output see the same dropped activations.
        ``fmask``: (batch, time) features mask threaded to sequence layers."""
        env = get_environment()
        cdt = env.compute_dtype
        if jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != cdt:
            x = x.astype(cdt)
        from deeplearning4j_tpu.nn.base import cast_floating
        params = cast_floating(params, cdt)
        new_state = dict(model_state)
        new_carries = {} if carries is not None else None
        last_input = x
        n = len(self.layers)
        # A pure chain: every layer boundary is a remat cut point. With
        # env.remat_segments on, each hidden layer's activations are
        # recomputed in the backward pass instead of saved — HBM traffic
        # traded for FLOPs (same policy as ComputationGraph._forward_remat).
        use_remat = (env.remat_segments and training and carries is None
                     and n > 2)
        for i, layer in enumerate(self.layers):
            k = _layer_key(i, layer)
            if i in self.conf.preprocessors:
                x = self.conf.preprocessors[i].pre_process(x, fmask)
            p = params.get(k, {})
            s = model_state.get(k, {})
            lrng = jax.random.fold_in(rng, i) if rng is not None else None
            if training and getattr(layer, "weight_noise", None) is not None:
                from deeplearning4j_tpu.nn.constraints import apply_weight_noise
                p = apply_weight_noise(
                    layer, p,
                    None if lrng is None else jax.random.fold_in(lrng, 7919))
            if i == n - 1 and hasattr(layer, "compute_loss"):
                x = layer._apply_input_dropout(x, layer._g, training, lrng)
                last_input = x
                x = layer.activate(p, x)
            elif carries is not None and isinstance(layer, BaseRecurrentLayer):
                x = layer._apply_input_dropout(x, layer._g, training, lrng)
                y, c_new = layer.forward_with_carry(
                    p, carries[k], x, training=training, rng=lrng, mask=fmask)
                new_carries[k] = c_new
                x = y
            else:
                if use_remat and i < n - 1:
                    def _fwd(p_, s_, x_, lrng_, fmask_, _l=layer):
                        return _l.forward(p_, s_, x_, training=True,
                                          rng=lrng_, mask=fmask_)
                    x, s_new = jax.checkpoint(_fwd)(p, s, x, lrng, fmask)
                else:
                    x, s_new = layer.forward(p, s, x, training=training,
                                             rng=lrng, mask=fmask)
                if s:
                    new_state[k] = s_new
            if fmask is not None and hasattr(layer, "transform_mask"):
                # layers that change the time axis (crop/pad) realign the mask
                fmask = layer.transform_mask(fmask)
        return x, last_input, new_state, new_carries

    def _loss(self, params, model_state, x, y, rng, fmask=None, lmask=None,
              carries=None, training: bool = True):
        out, last_in, new_state, new_carries = self._forward(
            params, model_state, x, training=training, rng=rng, fmask=fmask,
            carries=carries)
        final = self.layers[-1]
        if not hasattr(final, "compute_loss"):
            raise ValueError("Last layer must be an output/loss layer to compute loss")
        k = _layer_key(len(self.layers) - 1, final)
        from deeplearning4j_tpu.nn.base import cast_floating
        final_p = cast_floating(params.get(k, {}), get_environment().compute_dtype)
        if training and getattr(final, "weight_noise", None) is not None \
                and rng is not None:
            # SAME noise keys as _forward's output-layer branch, so the loss
            # sees exactly the weights the forward activations used
            from deeplearning4j_tpu.nn.constraints import apply_weight_noise
            lrng = jax.random.fold_in(rng, len(self.layers) - 1)
            final_p = apply_weight_noise(final, final_p,
                                         jax.random.fold_in(lrng, 7919))
        loss = final.compute_loss(final_p, last_in, y, mask=lmask,
                                  state=model_state.get(k, {}))
        loss = loss + self._reg_score(params)
        # differentiable auxiliary losses surfaced by layers through the
        # state channel (e.g. MoE load balancing) — same trace, so grads
        # flow. Training-only: score() reports the data loss, not training
        # regularizers.
        if training:
            for s2 in new_state.values():
                if isinstance(s2, dict) and "_aux_loss" in s2:
                    loss = loss + s2["_aux_loss"]
        if training and hasattr(final, "update_state_with_labels"):
            new_state = dict(new_state)
            new_state[k] = final.update_state_with_labels(
                model_state.get(k, {}), jax.lax.stop_gradient(last_in), y)
        return loss, (new_state, new_carries)

    def _reg_score(self, params):
        """l1/l2 penalty (reference: score includes regularization terms).
        Walks nested param trees (e.g. Bidirectional {'fwd': .., 'bwd': ..})
        by path, matching the weight-decay mask semantics."""
        g = self.conf.global_conf
        total = jnp.zeros((), jnp.float32)
        for i, layer in enumerate(self.layers):
            k = _layer_key(i, layer)
            if k not in params:
                continue
            l1 = layer.l1 if layer.l1 is not None else g.l1
            l2 = layer.l2 if layer.l2 is not None else g.l2
            if not l1 and not l2:
                continue
            reg_keys = set(layer.regularizable_params())
            leaves = jax.tree_util.tree_flatten_with_path(params[k])[0]
            for path, w in leaves:
                if any(getattr(p, "key", None) in reg_keys for p in path):
                    if l1:
                        total = total + l1 * jnp.sum(jnp.abs(w))
                    if l2:
                        total = total + 0.5 * l2 * jnp.sum(w * w)
        return total

    # ------------------------------------------------------------ train step
    def _apply_constraints(self, params):
        """Post-update projections (reference applyConstraints) — pure ops
        inside the same compiled step."""
        from deeplearning4j_tpu.nn.constraints import apply_layer_constraints
        if not any(getattr(l, "constraints", None)
                   or getattr(l, "bias_constraints", None)
                   for l in self.layers):
            return params
        out = dict(params)
        for i, layer in enumerate(self.layers):
            k = _layer_key(i, layer)
            if k in out:
                out[k] = apply_layer_constraints(layer, out[k])
        return out

    def _train_step_fn(self):
        def train_step(ts: TrainState, x, y, rng, fmask, lmask):
            (loss, (new_state, _)), grads = jax.value_and_grad(self._loss, has_aux=True)(
                ts.params, ts.model_state, x, y, rng, fmask, lmask)
            updates, new_opt = self._tx.update(grads, ts.opt_state, ts.params)
            new_params = self._apply_constraints(
                optax.apply_updates(ts.params, updates))
            return TrainState(params=new_params, model_state=new_state,
                              opt_state=new_opt, step=ts.step + 1), loss

        return train_step

    def _make_train_step(self):
        return jax.jit(self._train_step_fn(), donate_argnums=(0,))

    def _make_packed_train_step(self):
        """Train step whose boundary carries flat-packed small leaves
        (see :mod:`deeplearning4j_tpu.runtime.state_packing`): same math,
        bit-identical results, ~4x fewer buffer handles per dispatch."""
        from deeplearning4j_tpu.runtime.state_packing import LeafPacker
        packer = LeafPacker(self.train_state)
        raw = self._train_step_fn()

        def packed_step(pts, x, y, rng, fmask, lmask):
            new_ts, loss = raw(packer.unpack(pts), x, y, rng, fmask, lmask)
            return packer.pack(new_ts), loss

        return jax.jit(packed_step, donate_argnums=(0,)), packer

    def _make_tbptt_step(self):
        """Train step with explicit recurrent carries (truncated BPTT)."""
        def step(ts: TrainState, carries, x, y, rng, fmask, lmask):
            (loss, (new_state, new_carries)), grads = jax.value_and_grad(
                self._loss, has_aux=True)(ts.params, ts.model_state, x, y, rng,
                                          fmask, lmask, carries)
            updates, new_opt = self._tx.update(grads, ts.opt_state, ts.params)
            new_params = optax.apply_updates(ts.params, updates)
            new_carries = jax.tree.map(jax.lax.stop_gradient, new_carries)
            return (TrainState(params=new_params, model_state=new_state,
                               opt_state=new_opt, step=ts.step + 1), new_carries, loss)

        return jax.jit(step, donate_argnums=(0, 1))

    def _jitted(self, name: str, factory):
        # remat is read at TRACE time, so flipping env.set_remat() must
        # produce a different cache entry (same rule as ComputationGraph)
        name = f"{name}@remat={get_environment().remat_segments}"
        if name not in self._jit_cache:
            self._jit_cache[name] = factory()
        return self._jit_cache[name]

    def _packed_cache_key(self) -> str:
        return f"packed_train_step@remat={get_environment().remat_segments}"

    def _jitted_packed_unrolled(self, k: int):
        """K same-shape batches per device dispatch (env.dispatch_unroll).
        Shares the single-step packer, so packed state flows between
        grouped and single dispatches. (Mask presence needs no key
        component: jit retraces on the None-vs-array pytree structure.)"""
        key = f"{self._packed_cache_key()}@unroll={k}"
        if key not in self._jit_cache:
            from deeplearning4j_tpu.runtime.state_packing import (
                make_unrolled_packed_step)
            _, packer = self._jitted_packed()
            self._jit_cache[key] = make_unrolled_packed_step(
                self._train_step_fn(), packer, k)
        return self._jit_cache[key]

    def _jitted_packed(self):
        # keyed directly by _packed_cache_key so the invalidation path in
        # PackedStepLoop.step pops the SAME key this populates
        key = self._packed_cache_key()
        if key not in self._jit_cache:
            self._jit_cache[key] = self._make_packed_train_step()
        return self._jit_cache[key]

    # ------------------------------------------------------------------- fit
    def fit(self, data, labels=None, epochs: int = 1, mask=None,
            labels_mask=None, prefetch_buffer: int = 0,
            profiler=None) -> "MultiLayerNetwork":
        """``fit(iterator)``, ``fit(iterator, epochs=N)`` or
        ``fit(x, y[, mask, labels_mask])`` (reference overloads —
        ``fit(features, labels, featuresMask, labelsMask)``). ``mask`` is the
        FEATURES mask; the labels mask defaults to it propagated through any
        time-axis-changing layers.

        ``prefetch_buffer > 0`` stages that many coerced batches on-device
        ahead of the step via a background
        :class:`~deeplearning4j_tpu.train.prefetch.DevicePrefetcher`
        (trajectory bit-identical to the synchronous loop); ``profiler``
        takes a :class:`~deeplearning4j_tpu.train.profiler.TrainingProfiler`
        that splits each iteration into data-wait/dispatch/step time."""
        if self.train_state is None:
            self.init()
        if labels is not None:
            from deeplearning4j_tpu.data.dataset import DataSet
            from deeplearning4j_tpu.data.iterators import ListDataSetIterator
            ds = DataSet(np.asarray(data), np.asarray(labels), features_mask=mask,
                         labels_mask=labels_mask)
            iterator = ListDataSetIterator([ds], batch_size=len(ds))
        else:
            iterator = data
        from deeplearning4j_tpu.runtime.state_packing import PackedStepLoop
        ploop = PackedStepLoop.for_network(self)
        if profiler is not None:
            profiler.start()
        try:
            self._fit_epochs(iterator, int(epochs), ploop,
                             prefetch_buffer=int(prefetch_buffer),
                             profiler=profiler)
        finally:
            # any exit path (incl. KeyboardInterrupt / iterator errors) must
            # leave train_state reflecting every completed step
            ploop.sync(release=True)
            if profiler is not None:
                profiler.stop()
        return self

    def _fit_epochs(self, iterator, epochs: int, ploop,
                    prefetch_buffer: int = 0, profiler=None) -> None:
        from deeplearning4j_tpu.runtime.state_packing import GroupedDispatch
        from deeplearning4j_tpu.train.prefetch import (AsyncLossDelivery,
                                                       stateless_listeners)

        def deliver(n, loss):
            self._score = loss
            self._iteration += 1
            for lst in self._listeners:
                if isinstance(lst, PerformanceListener):
                    lst.record_batch(n)
                lst.iteration_done(self, self._iteration, self._epoch, loss)

        # async loss readback: with only stateless listeners, delivery moves
        # to a completion thread (same callbacks, same order) so a listener
        # reading float(loss) no longer blocks dispatch of the next step; a
        # state-reading listener forces the synchronous path (it must see
        # ITS iteration's post-step train_state). No listeners and no
        # profiler = nothing worth a thread: deliver inline.
        adel = (AsyncLossDelivery(deliver, profiler=profiler)
                if (self._listeners or profiler is not None)
                and stateless_listeners(self) else None)
        # only the batch SIZE crosses into the delivery queue — queued step
        # args would pin full device batches for up to max_pending steps
        sink = adel.submit if adel is not None else deliver
        gd = GroupedDispatch(
            # with a state-reading listener, packing is off and batches must
            # dispatch one at a time so iteration_done sees fresh state
            unroll=(get_environment().dispatch_unroll if ploop.enabled else 1),
            compatible=_group_compatible,
            run_single=lambda a: ploop.step(*a)[0],
            run_group=ploop.step_group,
            deliver=lambda args, loss: sink(args[0].shape[0], loss))
        try:
            self._run_epochs(
                iterator, epochs, ploop, gd,
                drain=(adel.flush if adel is not None else (lambda: None)),
                prefetch_buffer=prefetch_buffer, profiler=profiler)
        finally:
            gd.drain_on_error()
            if adel is not None:
                adel.shutdown()  # never raises; original errors win
        if adel is not None:
            adel.raise_pending()

    def _run_epochs(self, iterator, epochs, ploop, gd, drain=lambda: None,
                    prefetch_buffer=0, profiler=None) -> None:
        from deeplearning4j_tpu.train.prefetch import (batch_source,
                                                       coerce_training_batch)
        from deeplearning4j_tpu.train.profiler import submit_timed
        for _ in range(epochs):
            for lst in self._listeners:
                lst.on_epoch_start(self, self._epoch)
            src = batch_source(iterator,
                               lambda ds: coerce_training_batch(self, ds),
                               prefetch_buffer, profiler)
            try:
                for x, y, fm, lm in src:
                    # zero-copy ref for listeners that sample activations
                    # (StatsListener histograms)
                    self._last_batch_features = x
                    if self.conf.tbptt_fwd_length and is_sequence_array(x):
                        if self.conf.global_conf.optimization_algo != \
                                "STOCHASTIC_GRADIENT_DESCENT":
                            raise NotImplementedError(
                                "truncated BPTT is only supported with "
                                "STOCHASTIC_GRADIENT_DESCENT (matching "
                                "ComputationGraph)")
                        gd.flush()
                        drain()  # tBPTT notifies listeners inline (ordered)
                        ploop.sync(release=True)  # tBPTT mutates train_state
                        self._fit_tbptt(x, y, fm, lm)
                        continue
                    if self.conf.global_conf.optimization_algo != \
                            "STOCHASTIC_GRADIENT_DESCENT":
                        from deeplearning4j_tpu.train.solvers import solver_fit_batch
                        gd.flush()
                        ploop.sync(release=True)  # solver mutates train_state
                        loss = solver_fit_batch(self, x, y, fm, lm)
                        gd._deliver((x, y, None, fm, lm), loss)  # same bookkeeping
                        continue
                    submit_timed(gd, (x, y, self.rng.next_key(), fm, lm),
                                 profiler)
            finally:
                src.close()
            gd.flush()
            drain()  # on_epoch_end must observe every iteration_done
            # no epoch-end sync: packing only runs when every listener is
            # stateless, so nothing reads train_state until fit() returns
            for lst in self._listeners:
                lst.on_epoch_end(self, self._epoch)
            self._epoch += 1

    def _fit_tbptt(self, x, y, fmask, lmask):
        """Split the time axis into tbptt-length chunks, carrying hidden state
        (reference: truncated BPTT in ``MultiLayerNetwork.fitHelper``)."""
        T = x.shape[1]
        L = int(self.conf.tbptt_fwd_length)
        carries = self._zero_carries(
            x.shape[0], carry_dtype(x, get_environment().compute_dtype))
        step_fn = self._jitted("tbptt_step", self._make_tbptt_step)
        for t0 in range(0, T, L):
            xs = slice_time(x, t0, L)
            ys = y[:, t0:t0 + L] if y.ndim >= 3 else y
            fms = fmask[:, t0:t0 + L] if fmask is not None else None
            lms = lmask[:, t0:t0 + L] if lmask is not None else None
            rng = self.rng.next_key()
            self.train_state, carries, loss = step_fn(
                self.train_state, carries, xs, ys, rng, fms, lms)
            self._score = loss
            self._iteration += 1
            for lst in self._listeners:
                lst.iteration_done(self, self._iteration, self._epoch, loss)

    # -------------------------------------------------------------- pretrain
    def pretrain(self, iterator, epochs: int = 1) -> "MultiLayerNetwork":
        """Greedy layer-wise unsupervised pretraining (reference
        ``MultiLayerNetwork.pretrain(DataSetIterator)``): every layer exposing
        a ``pretrain_loss`` (VAE, AutoEncoder) is trained in order on the
        unsupervised objective, with the layers below it frozen as a feature
        extractor."""
        for i, layer in enumerate(self.layers):
            if hasattr(layer, "pretrain_loss"):
                self.pretrain_layer(i, iterator, epochs=epochs)
        return self

    def pretrain_layer(self, i: int, iterator, epochs: int = 1) -> "MultiLayerNetwork":
        """Pretrain layer ``i`` only (reference ``pretrainLayer``). One jitted
        donated step: stop-gradient sub-forward through layers < i, then a
        gradient step on layer i's unsupervised loss."""
        if self.train_state is None:
            self.init()
        layer = self.layers[i]
        if not hasattr(layer, "pretrain_loss"):
            return self
        k = _layer_key(i, layer)
        g = self.conf.global_conf
        upd: Updater = layer.updater if layer.updater is not None else (
            g.updater if g.updater is not None else Sgd(0.1))
        tx = upd.make()

        def sub_input(params, model_state, x):
            cur = x
            for j in range(i):
                lay = self.layers[j]
                if j in self.conf.preprocessors:
                    cur = self.conf.preprocessors[j].pre_process(cur, None)
                cur, _ = lay.forward(params.get(_layer_key(j, lay), {}),
                                     model_state.get(_layer_key(j, lay), {}),
                                     cur, training=False, rng=None)
            if i in self.conf.preprocessors:
                cur = self.conf.preprocessors[i].pre_process(cur, None)
            return cur

        def step(layer_params, opt_state, below_params, model_state, x, rng):
            inp = jax.lax.stop_gradient(sub_input(below_params, model_state, x))
            loss, grads = jax.value_and_grad(
                lambda p: layer.pretrain_loss(p, inp, rng))(layer_params)
            updates, opt_state = tx.update(grads, opt_state, layer_params)
            return optax.apply_updates(layer_params, updates), opt_state, loss

        step_fn = self._jitted(f"pretrain_{i}", lambda: jax.jit(step, donate_argnums=(0, 1)))
        layer_params = self.train_state.params[k]
        # layer_params is donated; it must NOT also alias in via below_params
        # (donation frees the buffer — the aliased copy would be deleted)
        below_params = {kk: v for kk, v in self.train_state.params.items() if kk != k}
        opt_state = tx.init(layer_params)
        for _ in range(int(epochs)):
            iterator.reset()
            for batch in iterator:
                x = jnp.asarray(batch.features)
                layer_params, opt_state, loss = step_fn(
                    layer_params, opt_state, below_params,
                    self.train_state.model_state, x, self.rng.next_key())
                self._score = loss
        new_params = dict(self.train_state.params)
        new_params[k] = layer_params
        self.train_state = dataclasses.replace(self.train_state, params=new_params)
        return self

    def _output_time_mask(self, fmask):
        """Features mask propagated through every time-axis-changing layer
        (crop/pad/upsample/strided conv): the default LABELS mask must align
        with the network OUTPUT's time axis, not the input's."""
        if fmask is None:
            return None
        m = fmask
        for layer in self.layers:
            if hasattr(layer, "transform_mask"):
                m = layer.transform_mask(m)
        return m

    def _zero_carries(self, batch: int, dtype) -> Dict[str, Any]:
        carries = {}
        for i, layer in enumerate(self.layers):
            if isinstance(layer, BaseRecurrentLayer):
                carries[_layer_key(i, layer)] = layer.init_carry(batch, dtype)
        return carries

    # ------------------------------------------------------------- inference
    def output(self, x, training: bool = False, mask=None):
        """Forward pass (reference ``output(INDArray)``)."""
        if self.train_state is None:
            self.init()

        def fwd(params, model_state, x_, m_):
            out, _, _, _ = self._forward(params, model_state, x_,
                                         training=False, rng=None, fmask=m_)
            return out

        fn = self._jitted("output", lambda: jax.jit(fwd))
        m = None if mask is None else jnp.asarray(mask)
        return fn(self.train_state.params, self.train_state.model_state,
                  jnp.asarray(x), m)

    def feed_forward(self, x, num_layers: Optional[int] = None):
        """All layer activations (reference ``feedForward``) — not jitted;
        debugging/inspection path. ``num_layers`` stops after that many
        layers (reference ``feedForwardToLayer``)."""
        acts = [jnp.asarray(x)]
        cur = acts[0]
        ts = self.train_state
        stop = len(self.layers) if num_layers is None else int(num_layers)
        for i, layer in enumerate(self.layers[:stop]):
            if i in self.conf.preprocessors:
                cur = self.conf.preprocessors[i].pre_process(cur)
            k = _layer_key(i, layer)
            cur, _ = layer.forward(ts.params.get(k, {}), ts.model_state.get(k, {}),
                                   cur, training=False, rng=None)
            acts.append(cur)
        return acts

    def feed_forward_to_layer(self, layer_num: int, x):
        """Reference ``feedForwardToLayer(layerNum, input)``: activations of
        the input plus layers ``0..layer_num`` inclusive."""
        return self.feed_forward(x, num_layers=layer_num + 1)

    # --------------------------------------------------- external errors
    def backprop_gradient(self, x, epsilon):
        """Reference external-errors mode (``backpropGradient(epsilon)``
        after ``feedForward``): given dL/dOutput produced OUTSIDE this
        network (e.g. this net is an embedded component of a larger system),
        return ``(param_gradients, dL/dInput)`` — one jitted vjp, no update."""
        if self.train_state is None:
            self.init()
        x = jnp.asarray(x)
        epsilon = jnp.asarray(epsilon)

        def fn(params, model_state, x_, eps):
            def f(p, xx):
                out, _, new_state, _ = self._forward(
                    p, model_state, xx, training=True, rng=None)
                return out, new_state
            out, vjp, _ = jax.vjp(f, params, x_, has_aux=True)
            gp, gx = vjp(eps.astype(out.dtype))
            return gp, gx

        fn = self._jitted("backprop_external", lambda: jax.jit(fn))
        return fn(self.train_state.params, self.train_state.model_state,
                  x, epsilon)

    def fit_external(self, x, epsilon):
        """External-errors TRAINING step: backprop ``epsilon`` (dL/dOutput)
        through the net and apply the configured updater — the reference's
        ``computeGradientAndScore``-with-external-errors + updater pattern,
        fused into one jitted donated step."""
        if self.train_state is None:
            self.init()
        x = jnp.asarray(x)
        epsilon = jnp.asarray(epsilon)

        def make():
            def step(ts: TrainState, x_, eps, rng):
                def f(p, xx):
                    out, _, new_state, _ = self._forward(
                        p, ts.model_state, xx, training=True, rng=rng)
                    return out, new_state
                out, vjp, new_state = jax.vjp(f, ts.params, x_, has_aux=True)
                gp, gx = vjp(eps.astype(out.dtype))
                gp = self._trainable(gp)
                updates, new_opt = self._tx.update(gp, ts.opt_state, ts.params)
                new_params = optax.apply_updates(ts.params, updates)
                return TrainState(params=new_params, model_state=new_state,
                                  opt_state=new_opt, step=ts.step + 1), gx
            return jax.jit(step, donate_argnums=(0,))

        fn = self._jitted("fit_external", make)
        self.train_state, gx = fn(self.train_state, x, epsilon,
                                  self.rng.next_key())
        self._iteration += 1
        return gx

    def _rnn_step_fn(self, training: bool = False):
        """The jitted ``(params, model_state, carries, x, rng) ->
        (out, new_carries)`` program behind every stateful-RNN entry point.
        One cache key per ``training`` flag: :meth:`rnn_time_step`,
        :meth:`rnn_activate_using_stored_state` and
        :meth:`rnn_time_step_external` all share the SAME compiled
        executable, so a serving-tier external step is bit-identical to
        the stored-state step at the same program shape."""
        def make():
            def fwd(params, model_state, carries, x_, rng):
                out, _, _, new_carries = self._forward(
                    params, model_state, x_, training=training, rng=rng,
                    carries=carries)
                return out, new_carries
            return jax.jit(fwd)

        return self._jitted(f"rnn_stored_state@train={training}", make)

    def rnn_activate_using_stored_state(self, x, training: bool = False,
                                        store_last_for_tbptt: bool = False):
        """Reference ``rnnActivateUsingStoredState``: forward a sequence
        starting from the STORED recurrent state; optionally keep the final
        state (the tBPTT carry behaviour). Returns the output activations."""
        if self.train_state is None:
            self.init()
        x = jnp.asarray(x)
        if self._rnn_carries is None:
            self._rnn_carries = self._zero_carries(
                x.shape[0], carry_dtype(x, get_environment().compute_dtype))
        fn = self._rnn_step_fn(training)
        rng = self.rng.next_key() if training else None
        out, new_carries = fn(self.train_state.params,
                              self.train_state.model_state,
                              self._rnn_carries, x, rng)
        if store_last_for_tbptt:
            self._rnn_carries = new_carries
        return out

    def score(self, dataset=None) -> float:
        """Loss on a DataSet (inference behaviour: no dropout, running BN
        stats — matching the reference's ``score(DataSet)``), or the most
        recent minibatch score when called with no argument."""
        if dataset is None:
            return float(self._score)
        x, y = jnp.asarray(dataset.features), jnp.asarray(dataset.labels)
        fm = None if dataset.features_mask is None else jnp.asarray(dataset.features_mask)
        lm = jnp.asarray(dataset.labels_mask) if dataset.labels_mask is not None \
            else (self._output_time_mask(fm) if y.ndim == 3 else None)

        def score_fn(params, model_state, x_, y_, fm_, lm_):
            loss, _ = self._loss(params, model_state, x_, y_, None, fm_, lm_,
                                 training=False)
            return loss

        fn = self._jitted("score", lambda: jax.jit(score_fn))
        return float(fn(self.train_state.params, self.train_state.model_state,
                        x, y, fm, lm))

    def evaluate(self, iterator):
        """Classification evaluation over an iterator (reference
        ``evaluate(DataSetIterator)``)."""
        from deeplearning4j_tpu.evaluation.evaluation import Evaluation
        ev = Evaluation()
        iterator.reset()
        for batch in iterator:
            out = self.output(batch.features, mask=batch.features_mask)
            m = batch.labels_mask if batch.labels_mask is not None else (
                None if batch.features_mask is None
                else np.asarray(self._output_time_mask(jnp.asarray(batch.features_mask))))
            ev.eval(np.asarray(batch.labels), np.asarray(out),
                    mask=None if m is None else np.asarray(m))
        return ev

    def evaluate_regression(self, iterator):
        from deeplearning4j_tpu.evaluation.regression import RegressionEvaluation
        ev = RegressionEvaluation()
        iterator.reset()
        for batch in iterator:
            out = self.output(batch.features)
            ev.eval(np.asarray(batch.labels), np.asarray(out))
        return ev

    def evaluate_roc(self, iterator, threshold_steps: int = 0):
        from deeplearning4j_tpu.evaluation.roc import ROC
        roc = ROC(threshold_steps)
        iterator.reset()
        for batch in iterator:
            out = self.output(batch.features)
            roc.eval(np.asarray(batch.labels), np.asarray(out))
        return roc

    # ------------------------------------------------ stateful RNN inference
    def rnn_time_step(self, x):
        """Stateful sequence inference (reference ``rnnTimeStep``): feeds a
        (batch, time, size) chunk, returns output and stores recurrent state
        for the next call. Same compiled program as
        :meth:`rnn_activate_using_stored_state`."""
        return self.rnn_activate_using_stored_state(
            x, training=False, store_last_for_tbptt=True)

    def rnn_clear_previous_state(self) -> None:
        self._rnn_carries = None

    def rnn_get_state(self):
        """Serializable copy of the stored recurrent state (reference
        ``rnnGetPreviousState``, whole network instead of per-layer): a
        pytree with numpy leaves whose dtypes match the carries exactly,
        or ``None`` when no state is stored. Round-trips bit-exactly
        through :meth:`rnn_set_state` — the contract the serving session
        store spills to disk."""
        if self._rnn_carries is None:
            return None
        return jax.tree.map(np.asarray, self._rnn_carries)

    def rnn_set_state(self, state) -> None:
        """Install a recurrent state previously captured with
        :meth:`rnn_get_state` (reference ``rnnSetPreviousState``); ``None``
        clears, like :meth:`rnn_clear_previous_state`. Leaf dtypes are
        preserved as given — no recast — so set(get()) is bit-exact."""
        self._rnn_carries = (None if state is None
                             else jax.tree.map(jnp.asarray, state))

    def rnn_zero_state(self, batch: int, like=None):
        """Fresh zero recurrent state for a ``batch``-row stream: the tree
        :meth:`rnn_time_step` would lazily create on its first call.
        ``like`` (an example input) pins the carry dtype the same way the
        stateful path does; without it the environment compute dtype is
        used."""
        if self.train_state is None:
            self.init()
        dt = (get_environment().compute_dtype if like is None else
              carry_dtype(jnp.asarray(like), get_environment().compute_dtype))
        return self._zero_carries(batch, dt)

    def rnn_time_step_external(self, x, state):
        """Pure-functional ``rnnTimeStep``: advance ``state`` (a tree from
        :meth:`rnn_get_state` / :meth:`rnn_zero_state`, or ``None`` for a
        fresh stream) by one input chunk WITHOUT touching the state stored
        on the network. Returns ``(out, new_state)``. Same compiled
        program as :meth:`rnn_time_step` — at equal program shape the two
        are bit-identical — which is what lets the serving session tier
        batch many independent streams through one executable."""
        if self.train_state is None:
            self.init()
        x = jnp.asarray(x)
        if state is None:
            state = self._zero_carries(
                x.shape[0], carry_dtype(x, get_environment().compute_dtype))
        fn = self._rnn_step_fn(training=False)
        out, new_state = fn(self.train_state.params,
                            self.train_state.model_state, state, x, None)
        return out, new_state

    # -------------------------------------------------------------- plumbing
    def set_listeners(self, *listeners: TrainingListener) -> None:
        self._listeners = list(listeners)

    def add_listeners(self, *listeners: TrainingListener) -> None:
        self._listeners.extend(listeners)

    def get_listeners(self) -> Sequence[TrainingListener]:
        return list(self._listeners)

    def params(self):
        return self.train_state.params if self.train_state else None

    def set_params(self, params) -> None:
        if self.train_state is None:
            self.init(params=params)
        else:
            self.train_state = dataclasses.replace(self.train_state, params=params)

    def num_params(self) -> int:
        if self.train_state is None:
            return 0
        return int(sum(np.prod(p.shape) for p in jax.tree.leaves(self.train_state.params)))

    def get_layer(self, key) -> Layer:
        """Layer by index or name (reference ``getLayer``)."""
        if isinstance(key, int):
            return self.layers[key]
        for i, l in enumerate(self.layers):
            if _layer_key(i, l) == key or l.name == key:
                return l
        raise KeyError(key)

    def summary(self) -> str:
        """Layer table: name, type, in->out shape, #params (reference
        ``MultiLayerNetwork.summary()``)."""
        if self.train_state is None:
            self.init()
        rows = [("idx", "name", "type", "nIn -> nOut", "params")]
        total = 0
        for i, layer in enumerate(self.layers):
            k = _layer_key(i, layer)
            p = self.train_state.params.get(k, {})
            n = int(sum(np.prod(w.shape) for w in jax.tree.leaves(p)))
            total += n
            it = (self.conf.layer_input_types[i]
                  if self.conf.layer_input_types else None)
            shape = ""
            if it is not None:
                try:
                    shape = f"{it.describe()} -> {layer.output_type(it).describe()}"
                except Exception:
                    shape = ""
            rows.append((str(i), k, type(layer).__name__, shape, f"{n:,}"))
        widths = [max(len(r[c]) for r in rows) for c in range(5)]
        lines = ["  ".join(v.ljust(w) for v, w in zip(r, widths))
                 for r in rows]
        lines.insert(1, "-" * len(lines[0]))
        lines.append(f"Total parameters: {total:,}")
        return "\n".join(lines)

    @property
    def iteration(self) -> int:
        return self._iteration

    @property
    def epoch(self) -> int:
        return self._epoch

    # serialization (reference ModelSerializer.writeModel / save+load methods)
    def save(self, path: str, save_updater: bool = True) -> None:
        from deeplearning4j_tpu.models.serializer import ModelSerializer
        ModelSerializer.write_model(self, path, save_updater=save_updater)

    @staticmethod
    def load(path: str, load_updater: bool = True) -> "MultiLayerNetwork":
        from deeplearning4j_tpu.models.serializer import ModelSerializer
        return ModelSerializer.restore_multi_layer_network(path, load_updater=load_updater)

    def clone(self) -> "MultiLayerNetwork":
        net = MultiLayerNetwork(MultiLayerConfiguration.from_dict(self.conf.to_dict()))
        if self.train_state is not None:
            net.init(params=jax.tree.map(jnp.copy, self.train_state.params))
            net.train_state = dataclasses.replace(
                net.train_state, model_state=jax.tree.map(jnp.copy, self.train_state.model_state))
        return net


def _mask_keys(params, keys):
    """Boolean mask pytree: True where the leaf's dict key is a regularizable
    param name (weight-decay applies to weights, not biases/norm scales)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, _: any(getattr(p, "key", None) in keys for p in path), params)
