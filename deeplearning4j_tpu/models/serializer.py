"""Single-file model archives.

Rebuild of upstream ``org.deeplearning4j.util.ModelSerializer``: a zip holding
``configuration.json`` (full config tree), ``coefficients.npz`` (params),
``updaterState.npz`` (optimizer moments — Adam m/v etc.), optional
``normalizer.npz``; ``restore_*(path, load_updater)`` resumes training exactly,
as in the reference. Pytree leaves are stored in deterministic
``tree_flatten`` order and restored against a freshly-initialised structure
(the flat-buffer analog of the reference's ``coefficients.bin``).

For sharded/async checkpoint-during-training use ``train.checkpoint`` (orbax)
instead; this format is the portable interchange artifact.
"""

from __future__ import annotations

import io
import json
import zipfile
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

_CONF = "configuration.json"
_COEFF = "coefficients.npz"
_UPDATER = "updaterState.npz"
_NORM = "normalizer.npz"
_META = "metadata.json"


def _save_pytree_npz(tree) -> bytes:
    leaves = jax.tree.leaves(tree)
    buf = io.BytesIO()
    np.savez(buf, **{f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)})
    return buf.getvalue()


def _load_pytree_npz(data: bytes, like):
    z = np.load(io.BytesIO(data))
    leaves = [z[f"leaf_{i}"] for i in range(len(z.files))]
    treedef = jax.tree.structure(like)
    like_leaves = jax.tree.leaves(like)
    if len(leaves) != len(like_leaves):
        raise ValueError(f"Archive has {len(leaves)} arrays; model expects {len(like_leaves)}")
    coerced = [jnp.asarray(l, dtype=ll.dtype) for l, ll in zip(leaves, like_leaves)]
    return jax.tree.unflatten(treedef, coerced)


class ModelSerializer:
    @staticmethod
    def write_model(net, path: str, save_updater: bool = True,
                    normalizer=None) -> None:
        import dataclasses
        kind = type(net).__name__
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr(_CONF, net.conf.to_json())
            # The RNG stream position rides along so a restored net does not
            # replay dropout keys from the seed (exact resume: save at step
            # N, restore, continue == an uninterrupted run).
            rng_state = net.rng.get_state()
            zf.writestr(_META, json.dumps({
                "model_type": kind,
                "iteration": net._iteration,
                "epoch": net._epoch,
                "rng_seed": rng_state["seed"],
                "rng_key": rng_state["key"],
                "framework": "deeplearning4j_tpu",
            }))
            ts = net.train_state
            zf.writestr(_COEFF, _save_pytree_npz({"params": ts.params,
                                                  "model_state": ts.model_state}))
            if save_updater:
                zf.writestr(_UPDATER, _save_pytree_npz(ts.opt_state))
            if normalizer is not None:
                buf = io.BytesIO()
                np.savez(buf, kind=type(normalizer).__name__, **normalizer._state())
                zf.writestr(_NORM, buf.getvalue())

    @staticmethod
    def restore_model(path: str, load_updater: bool = True):
        """Type-dispatching restore (reference ``ModelGuesser`` /
        ``ModelSerializer.restoreMultiLayerNetworkAndNormalizer`` family):
        reads the archive metadata and returns the right network class.
        Quantized archives (``quantization.json`` member, written by
        ``serving.quantize.quantize_archive``) restore as a
        ``QuantizedModel`` — int8 weights + dtype policy — so every load
        path (registry, fleet workers) serves them first-class."""
        with zipfile.ZipFile(path) as zf:
            names = zf.namelist()
            kind = (json.loads(zf.read(_META).decode()).get("model_type")
                    if _META in names else None)
            quantized = "quantization.json" in names
        if quantized:
            from deeplearning4j_tpu.serving.quantize import QuantizedModel
            return QuantizedModel.restore(path)
        if kind == "ComputationGraph":
            return ModelSerializer.restore_computation_graph(path, load_updater)
        return ModelSerializer.restore_multi_layer_network(path, load_updater)

    @staticmethod
    def restore_multi_layer_network(path: str, load_updater: bool = True):
        from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
        from deeplearning4j_tpu.nn.config import MultiLayerConfiguration
        with zipfile.ZipFile(path) as zf:
            conf = MultiLayerConfiguration.from_json(zf.read(_CONF).decode())
            net = MultiLayerNetwork(conf).init()
            ModelSerializer._restore_state(zf, net, load_updater)
        return net

    @staticmethod
    def restore_computation_graph(path: str, load_updater: bool = True):
        from deeplearning4j_tpu.models.computation_graph import (
            ComputationGraph, ComputationGraphConfiguration)
        with zipfile.ZipFile(path) as zf:
            conf = ComputationGraphConfiguration.from_json(zf.read(_CONF).decode())
            net = ComputationGraph(conf).init()
            ModelSerializer._restore_state(zf, net, load_updater)
        return net

    @staticmethod
    def _restore_state(zf: zipfile.ZipFile, net, load_updater: bool):
        import dataclasses
        ts = net.train_state
        coeff = _load_pytree_npz(zf.read(_COEFF),
                                 {"params": ts.params, "model_state": ts.model_state})
        new_ts = dataclasses.replace(ts, params=coeff["params"],
                                     model_state=coeff["model_state"])
        if load_updater and _UPDATER in zf.namelist():
            new_ts = dataclasses.replace(
                new_ts, opt_state=_load_pytree_npz(zf.read(_UPDATER), ts.opt_state))
        meta = json.loads(zf.read(_META).decode()) if _META in zf.namelist() else {}
        net._iteration = int(meta.get("iteration", 0))
        net._epoch = int(meta.get("epoch", 0))
        if meta.get("rng_seed") is not None:
            net.rng.set_state({"seed": meta["rng_seed"],
                               "key": meta.get("rng_key")})
        net.train_state = new_ts

    @staticmethod
    def restore_normalizer(path: str):
        from deeplearning4j_tpu.data.normalizers import Normalizer
        with zipfile.ZipFile(path) as zf:
            if _NORM not in zf.namelist():
                return None
            import tempfile, os
            with tempfile.NamedTemporaryFile(suffix=".npz", delete=False) as f:
                f.write(zf.read(_NORM))
                tmp = f.name
            try:
                return Normalizer.load(tmp)
            finally:
                os.unlink(tmp)
