"""Transfer learning.

Rebuild of upstream
``org.deeplearning4j.nn.transferlearning.{TransferLearning, FineTuneConfiguration}``:
take a trained network, freeze a prefix, replace/append head layers, keep the
pretrained weights for retained layers. Frozen layers stay in the params
pytree but receive zero updates (``optax.set_to_zero`` via ``Layer.frozen``) —
the functional analog of the reference's ``FrozenLayer`` wrapper.
"""

from __future__ import annotations

import copy
import dataclasses
from typing import List, Optional

import jax

from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork, _layer_key
from deeplearning4j_tpu.nn.config import MultiLayerConfiguration


@dataclasses.dataclass
class FineTuneConfiguration:
    """Global overrides applied to all non-frozen layers (reference
    ``FineTuneConfiguration``)."""

    updater: object = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    dropout: Optional[float] = None
    seed: Optional[int] = None

    def apply(self, conf: MultiLayerConfiguration) -> None:
        g = conf.global_conf
        if self.updater is not None:
            g.updater = self.updater
        if self.l1 is not None:
            g.l1 = self.l1
        if self.l2 is not None:
            g.l2 = self.l2
        if self.dropout is not None:
            g.dropout = self.dropout
        if self.seed is not None:
            g.seed = self.seed


class TransferLearning:
    """Builder (reference ``TransferLearning.Builder``)::

        net2 = (TransferLearning.builder(net)
                .fine_tune_configuration(FineTuneConfiguration(updater=Adam(1e-4)))
                .set_feature_extractor(3)        # freeze layers 0..3
                .remove_output_layer()
                .add_layer(OutputLayer(n_out=5, activation="softmax"))
                .build())
    """

    @staticmethod
    def builder(net: MultiLayerNetwork) -> "TransferLearning.Builder":
        return TransferLearning.Builder(net)

    class Builder:
        def __init__(self, net: MultiLayerNetwork):
            self._net = net
            self._conf = MultiLayerConfiguration.from_dict(net.conf.to_dict())
            self._old_params = net.train_state.params if net.train_state else {}
            self._old_state = net.train_state.model_state if net.train_state else {}
            self._freeze_until: Optional[int] = None
            self._fine_tune: Optional[FineTuneConfiguration] = None
            self._removed_from: Optional[int] = None
            self._added: List = []

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._fine_tune = ftc
            return self

        def set_feature_extractor(self, layer_index: int):
            """Freeze layers [0..layer_index] inclusive (reference semantics)."""
            self._freeze_until = int(layer_index)
            return self

        def remove_output_layer(self):
            return self.remove_layers_from_output(1)

        def remove_layers_from_output(self, n: int):
            self._removed_from = len(self._conf.layers) - int(n)
            return self

        def add_layer(self, layer):
            self._added.append(layer)
            return self

        def build(self) -> MultiLayerNetwork:
            conf = self._conf
            if self._fine_tune:
                self._fine_tune.apply(conf)
            keep = conf.layers[: self._removed_from] if self._removed_from is not None \
                else list(conf.layers)
            kept_n = len(keep)
            layers = keep + list(self._added)
            if self._freeze_until is not None:
                for i in range(min(self._freeze_until + 1, len(layers))):
                    layers[i].frozen = True
            conf.layers = layers
            conf.preprocessors = {i: pp for i, pp in conf.preprocessors.items()
                                  if i < kept_n}
            conf._infer_shapes()
            net = MultiLayerNetwork(conf).init()
            # graft pretrained params AND model state (batch-norm running
            # stats!) for kept layers; new layers keep fresh init
            import jax.numpy as jnp
            new_params = dict(net.train_state.params)
            new_state = dict(net.train_state.model_state)
            for i, layer in enumerate(conf.layers[:kept_n]):
                k = _layer_key(i, layer)
                if k in self._old_params:
                    # real copies: both nets run donated train steps, and a
                    # shared buffer would be deleted by whichever fits first
                    new_params[k] = jax.tree.map(jnp.copy, self._old_params[k])
                if k in self._old_state:
                    new_state[k] = jax.tree.map(jnp.copy, self._old_state[k])
            net.set_params(new_params)
            net.train_state = dataclasses.replace(net.train_state,
                                                  model_state=new_state)
            return net


class TransferLearningGraph:
    """Transfer learning on a ComputationGraph (reference
    ``TransferLearning.GraphBuilder``)::

        net2 = (TransferLearning.graph_builder(net)
                .fine_tune_configuration(FineTuneConfiguration(updater=Adam(1e-4)))
                .set_feature_extractor("pool")     # freeze "pool" + ancestors
                .remove_vertex_and_connections("out")
                .add_layer("out2", OutputLayer(n_out=5, activation="softmax"), "pool")
                .set_outputs("out2")
                .build())
    """

    class Builder:
        def __init__(self, net):
            from deeplearning4j_tpu.models.computation_graph import (
                ComputationGraphConfiguration)
            self._net = net
            self._conf = ComputationGraphConfiguration.from_dict(net.conf.to_dict())
            self._old_params = net.train_state.params if net.train_state else {}
            self._old_state = net.train_state.model_state if net.train_state else {}
            self._fine_tune: Optional[FineTuneConfiguration] = None
            self._freeze_at: List[str] = []
            self._removed: set = set()
            self._added_names: List[str] = []

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._fine_tune = ftc
            return self

        def set_feature_extractor(self, *vertex_names: str):
            """Freeze the named vertices and every ancestor (reference
            semantics: everything up to and including these is a fixed
            feature extractor)."""
            self._freeze_at = list(vertex_names)
            return self

        def remove_vertex_and_connections(self, name: str):
            """Remove a vertex and everything downstream of it."""
            doomed = {name}
            changed = True
            while changed:
                changed = False
                for n in self._conf.nodes:
                    if n.name not in doomed and any(i in doomed for i in n.inputs):
                        doomed.add(n.name)
                        changed = True
            self._removed |= doomed
            return self

        def add_layer(self, name: str, layer, *inputs: str):
            from deeplearning4j_tpu.models.computation_graph import GraphNode
            layer.name = name
            self._conf.nodes.append(GraphNode(name, "layer", layer, list(inputs)))
            self._added_names.append(name)
            return self

        def add_vertex(self, name: str, vertex, *inputs: str):
            from deeplearning4j_tpu.models.computation_graph import GraphNode
            self._conf.nodes.append(GraphNode(name, "vertex", vertex, list(inputs)))
            self._added_names.append(name)
            return self

        def set_outputs(self, *names: str):
            self._conf.outputs = list(names)
            return self

        def _ancestors(self, names: List[str]) -> set:
            by_name = {n.name: n for n in self._conf.nodes}
            seen = set()

            def walk(n):
                if n in seen or n in self._conf.inputs:
                    return
                if n not in by_name:
                    raise ValueError(
                        f"set_feature_extractor target {n!r} is not a graph "
                        f"vertex (typo, or removed by "
                        f"remove_vertex_and_connections)")
                seen.add(n)
                for dep in by_name[n].inputs:
                    walk(dep)

            for n in names:
                walk(n)
            return seen

        def build(self):
            from deeplearning4j_tpu.models.computation_graph import ComputationGraph
            conf = self._conf
            if self._fine_tune:
                self._fine_tune.apply(conf)  # acts on global_conf only
            conf.nodes = [n for n in conf.nodes if n.name not in self._removed]
            missing = [o for o in conf.outputs if o in self._removed]
            if missing:
                raise ValueError(
                    f"outputs {missing} were removed; call set_outputs(...)")
            if self._freeze_at:
                for name in self._ancestors(self._freeze_at):
                    node = conf.node(name)
                    if node.kind == "layer":
                        node.obj.frozen = True
            conf._toposort_and_infer()
            net = ComputationGraph(conf).init()
            import jax.numpy as jnp
            new_params = dict(net.train_state.params)
            new_state = dict(net.train_state.model_state)
            for n in conf.nodes:
                if n.name in self._added_names:
                    continue
                if n.name in self._old_params:
                    new_params[n.name] = jax.tree.map(
                        jnp.copy, self._old_params[n.name])
                if n.name in self._old_state:
                    # batch-norm running stats etc. belong to the pretrained
                    # feature extractor as much as its weights do
                    new_state[n.name] = jax.tree.map(
                        jnp.copy, self._old_state[n.name])
            net.set_params(new_params)
            net.train_state = dataclasses.replace(net.train_state,
                                                  model_state=new_state)
            return net


TransferLearning.graph_builder = staticmethod(
    lambda net: TransferLearningGraph.Builder(net))
