"""Transfer learning.

Rebuild of upstream
``org.deeplearning4j.nn.transferlearning.{TransferLearning, FineTuneConfiguration}``:
take a trained network, freeze a prefix, replace/append head layers, keep the
pretrained weights for retained layers. Frozen layers stay in the params
pytree but receive zero updates (``optax.set_to_zero`` via ``Layer.frozen``) —
the functional analog of the reference's ``FrozenLayer`` wrapper.
"""

from __future__ import annotations

import copy
import dataclasses
from typing import List, Optional

import jax

from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork, _layer_key
from deeplearning4j_tpu.nn.config import MultiLayerConfiguration


@dataclasses.dataclass
class FineTuneConfiguration:
    """Global overrides applied to all non-frozen layers (reference
    ``FineTuneConfiguration``)."""

    updater: object = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    dropout: Optional[float] = None
    seed: Optional[int] = None

    def apply(self, conf: MultiLayerConfiguration) -> None:
        g = conf.global_conf
        if self.updater is not None:
            g.updater = self.updater
        if self.l1 is not None:
            g.l1 = self.l1
        if self.l2 is not None:
            g.l2 = self.l2
        if self.dropout is not None:
            g.dropout = self.dropout
        if self.seed is not None:
            g.seed = self.seed


class TransferLearning:
    """Builder (reference ``TransferLearning.Builder``)::

        net2 = (TransferLearning.builder(net)
                .fine_tune_configuration(FineTuneConfiguration(updater=Adam(1e-4)))
                .set_feature_extractor(3)        # freeze layers 0..3
                .remove_output_layer()
                .add_layer(OutputLayer(n_out=5, activation="softmax"))
                .build())
    """

    @staticmethod
    def builder(net: MultiLayerNetwork) -> "TransferLearning.Builder":
        return TransferLearning.Builder(net)

    class Builder:
        def __init__(self, net: MultiLayerNetwork):
            self._net = net
            self._conf = MultiLayerConfiguration.from_dict(net.conf.to_dict())
            self._old_params = net.train_state.params if net.train_state else {}
            self._freeze_until: Optional[int] = None
            self._fine_tune: Optional[FineTuneConfiguration] = None
            self._removed_from: Optional[int] = None
            self._added: List = []

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._fine_tune = ftc
            return self

        def set_feature_extractor(self, layer_index: int):
            """Freeze layers [0..layer_index] inclusive (reference semantics)."""
            self._freeze_until = int(layer_index)
            return self

        def remove_output_layer(self):
            return self.remove_layers_from_output(1)

        def remove_layers_from_output(self, n: int):
            self._removed_from = len(self._conf.layers) - int(n)
            return self

        def add_layer(self, layer):
            self._added.append(layer)
            return self

        def build(self) -> MultiLayerNetwork:
            conf = self._conf
            if self._fine_tune:
                self._fine_tune.apply(conf)
            keep = conf.layers[: self._removed_from] if self._removed_from is not None \
                else list(conf.layers)
            kept_n = len(keep)
            layers = keep + list(self._added)
            if self._freeze_until is not None:
                for i in range(min(self._freeze_until + 1, len(layers))):
                    layers[i].frozen = True
            conf.layers = layers
            conf.preprocessors = {i: pp for i, pp in conf.preprocessors.items()
                                  if i < kept_n}
            conf._infer_shapes()
            net = MultiLayerNetwork(conf).init()
            # graft pretrained params for kept layers (new layers keep fresh init)
            new_params = dict(net.train_state.params)
            for i, layer in enumerate(conf.layers[:kept_n]):
                k = _layer_key(i, layer)
                if k in self._old_params:
                    new_params[k] = jax.tree.map(lambda a: a, self._old_params[k])
            net.set_params(new_params)
            return net
