"""ComputationGraph: arbitrary-DAG network with the same jitted engine.

Rebuild of upstream ``org.deeplearning4j.nn.graph.ComputationGraph`` +
``ComputationGraphConfiguration.GraphBuilder``: named inputs, layer nodes and
merge/elementwise/... vertices, multiple outputs, topological execution.
TPU-first: the whole DAG traces into ONE jitted program (the reference walks
the topo order dispatching per-op); multi-output losses sum (with optional
weighting) exactly like the reference's multi-output training.

Usage (mirrors the reference)::

    conf = (NeuralNetConfiguration.builder().updater(Adam(1e-3)).graph_builder()
            .add_inputs("in")
            .add_layer("conv1", ConvolutionLayer(n_out=32, ...), "in")
            .add_layer("fc", DenseLayer(n_out=128, ...), "conv1")
            .add_layer("out", OutputLayer(n_out=10, ...), "fc")
            .set_outputs("out")
            .set_input_types(InputType.convolutional(28, 28, 1))
            .build())
    net = ComputationGraph(conf).init()
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeplearning4j_tpu.nn.base import GlobalConfig, Layer
from deeplearning4j_tpu.nn.core_layers import LossLayer, OutputLayer
from deeplearning4j_tpu.nn.graph_vertices import GraphVertex
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.models.multi_layer_network import TrainState, _mask_keys
from deeplearning4j_tpu.nn.base import cast_floating
from deeplearning4j_tpu.models._tbptt import (carry_dtype, is_sequence_array,
                                               seq_length, slice_time)
from deeplearning4j_tpu.nn.recurrent_layers import BaseRecurrentLayer
from deeplearning4j_tpu.runtime.environment import get_environment
from deeplearning4j_tpu.runtime.rng import RngManager
from deeplearning4j_tpu.train.listeners import TrainingListener
from deeplearning4j_tpu.train.updaters import Sgd, Updater, gradient_normalization_transform


@dataclasses.dataclass
class GraphNode:
    name: str
    kind: str  # "layer" | "vertex"
    obj: Any  # Layer or GraphVertex
    inputs: List[str]


class GraphBuilder:
    def __init__(self, g: GlobalConfig):
        self._g = g
        self._inputs: List[str] = []
        self._nodes: List[GraphNode] = []
        self._outputs: List[str] = []
        self._input_types: List[InputType] = []
        self._tbptt_fwd: Optional[int] = None

    def add_inputs(self, *names: str) -> "GraphBuilder":
        self._inputs.extend(names)
        return self

    def add_layer(self, name: str, layer: Layer, *inputs: str) -> "GraphBuilder":
        layer.name = name
        self._nodes.append(GraphNode(name, "layer", layer, list(inputs)))
        return self

    def add_vertex(self, name: str, vertex: GraphVertex, *inputs: str) -> "GraphBuilder":
        self._nodes.append(GraphNode(name, "vertex", vertex, list(inputs)))
        return self

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._outputs = list(names)
        return self

    def set_input_types(self, *types: InputType) -> "GraphBuilder":
        self._input_types = list(types)
        return self

    def tbptt_fwd_length(self, n: int) -> "GraphBuilder":
        self._tbptt_fwd = int(n)
        return self

    def build(self) -> "ComputationGraphConfiguration":
        conf = ComputationGraphConfiguration(
            global_conf=self._g, inputs=self._inputs, nodes=self._nodes,
            outputs=self._outputs, input_types=self._input_types,
            tbptt_fwd_length=self._tbptt_fwd)
        conf._toposort_and_infer()
        return conf


@dataclasses.dataclass
class ComputationGraphConfiguration:
    global_conf: GlobalConfig
    inputs: List[str]
    nodes: List[GraphNode]
    outputs: List[str]
    input_types: List[InputType] = dataclasses.field(default_factory=list)
    tbptt_fwd_length: Optional[int] = None
    topo_order: List[str] = dataclasses.field(default_factory=list)
    node_input_types: Dict[str, InputType] = dataclasses.field(default_factory=dict)

    def node(self, name: str) -> GraphNode:
        for n in self.nodes:
            if n.name == name:
                return n
        raise KeyError(name)

    def _toposort_and_infer(self) -> None:
        by_name = {n.name: n for n in self.nodes}
        dup = len(by_name) != len(self.nodes)
        if dup:
            raise ValueError("Duplicate node names in graph")
        visited: Dict[str, int] = {}
        order: List[str] = []

        def visit(name: str):
            if name in self.inputs:
                return
            st = visited.get(name, 0)
            if st == 1:
                raise ValueError(f"Cycle detected at {name!r}")
            if st == 2:
                return
            visited[name] = 1
            for dep in by_name[name].inputs:
                visit(dep)
            visited[name] = 2
            order.append(name)

        for out in self.outputs:
            visit(out)
        # include any stragglers (nodes not reachable from outputs)
        for n in self.nodes:
            visit(n.name)
        self.topo_order = order

        # shape inference
        types: Dict[str, InputType] = {}
        for i, name in enumerate(self.inputs):
            if i < len(self.input_types):
                types[name] = self.input_types[i]
        for name in self.topo_order:
            node = by_name[name]
            in_types = [types.get(i) for i in node.inputs]
            if any(t is None for t in in_types):
                self.node_input_types[name] = None
                types[name] = None
                continue
            if node.kind == "layer":
                from deeplearning4j_tpu.nn.config import MultiLayerConfiguration
                pp = MultiLayerConfiguration._auto_preprocessor(in_types[0], node.obj)
                if pp is not None:
                    node.inputs_preprocessor = pp
                    in_types[0] = pp.output_type(in_types[0])
                else:
                    node.inputs_preprocessor = getattr(node, "inputs_preprocessor", None)
                self.node_input_types[name] = in_types[0]
                types[name] = node.obj.output_type(in_types[0])
            else:
                self.node_input_types[name] = in_types[0]
                types[name] = node.obj.output_type(*in_types)
        self.output_types = [types.get(o) for o in self.outputs]

    # ---- serde ----
    def to_dict(self) -> dict:
        g = dataclasses.asdict(self.global_conf)
        if self.global_conf.updater is not None and hasattr(self.global_conf.updater, "to_dict"):
            g["updater"] = self.global_conf.updater.to_dict()
        for k in ("weight_init", "activation"):
            v = g.get(k)
            if hasattr(v, "value"):
                g[k] = v.value
        if g.get("dtype") is not None:
            g["dtype"] = jnp.dtype(g["dtype"]).name
        return {
            "model_type": "ComputationGraph",
            "global_conf": g,
            "inputs": self.inputs,
            "outputs": self.outputs,
            "input_types": [t.to_dict() for t in self.input_types],
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "nodes": [{"name": n.name, "kind": n.kind, "inputs": n.inputs,
                       "obj": n.obj.to_dict()} for n in self.nodes],
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    @staticmethod
    def from_dict(d: dict) -> "ComputationGraphConfiguration":
        import dataclasses as dc
        g_d = dict(d["global_conf"])
        if isinstance(g_d.get("updater"), dict):
            g_d["updater"] = Updater.from_dict(g_d["updater"])
        if isinstance(g_d.get("dtype"), str):
            g_d["dtype"] = jnp.dtype(g_d["dtype"]).type
        from deeplearning4j_tpu.ops.initializers import WeightInit
        if g_d.get("weight_init"):
            g_d["weight_init"] = WeightInit(g_d["weight_init"])
        g = GlobalConfig(**{k: v for k, v in g_d.items()
                            if k in {f.name for f in dc.fields(GlobalConfig)}})
        nodes = []
        for nd in d["nodes"]:
            obj = Layer.from_dict(nd["obj"]) if nd["kind"] == "layer" \
                else GraphVertex.from_dict(nd["obj"])
            nodes.append(GraphNode(nd["name"], nd["kind"], obj, list(nd["inputs"])))
        conf = ComputationGraphConfiguration(
            global_conf=g, inputs=list(d["inputs"]), nodes=nodes,
            outputs=list(d["outputs"]),
            input_types=[InputType.from_dict(t) for t in d.get("input_types", [])],
            tbptt_fwd_length=d.get("tbptt_fwd_length"))
        conf._toposort_and_infer()
        return conf

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        return ComputationGraphConfiguration.from_dict(json.loads(s))


def _cg_group_compatible(a, b) -> bool:
    """Whether two buffered (inputs, labels, rng, masks) tuples may share
    one unrolled dispatch: same input/label shapes and mask presence."""
    ia, la, _, ma = a
    ib, lb, _, mb = b
    if set(ia) != set(ib) or len(la) != len(lb):
        return False
    if any(ia[n].shape != ib[n].shape for n in ia):
        return False
    if any(x.shape != y.shape for x, y in zip(la, lb)):
        return False
    if (ma is None) != (mb is None):
        return False
    if ma is not None:
        if set(ma) != set(mb):
            return False
        for n in ma:
            if (ma[n] is None) != (mb[n] is None):
                return False
            if ma[n] is not None and ma[n].shape != mb[n].shape:
                return False
    return True


class ComputationGraph:
    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        for n in conf.nodes:
            if n.kind == "layer":
                n.obj._g = conf.global_conf
        self.rng = RngManager(conf.global_conf.seed)
        self.train_state: Optional[TrainState] = None
        self._listeners: List[TrainingListener] = []
        self._iteration = 0
        self._epoch = 0
        self._score = float("nan")
        self._tx: Optional[optax.GradientTransformation] = None
        self._jit_cache: Dict[str, Any] = {}
        self._remat_segs: Optional[List[List[str]]] = None

    @property
    def layers(self):
        return [n.obj for n in self.conf.nodes if n.kind == "layer"]

    # ------------------------------------------------------------------ init
    def init(self, params: Optional[Dict] = None) -> "ComputationGraph":
        g = self.conf.global_conf
        if g.dtype is None:
            g = dataclasses.replace(g, dtype=get_environment().default_dtype)
        def init_all(key):
            ps: Dict[str, Dict] = {}
            ss: Dict[str, Dict] = {}
            for i, name in enumerate(self.conf.topo_order):
                node = self.conf.node(name)
                if node.kind != "layer":
                    continue
                it = self.conf.node_input_types.get(name)
                p, s = node.obj.init(jax.random.fold_in(key, i), it, g)
                if p:
                    ps[name] = p
                if s:
                    ss[name] = s
            return ps, ss

        if params is not None:
            # only the non-trainable state is needed; returning just it lets
            # XLA dead-code-eliminate the (discarded) param initialization
            new_params = params
            model_state = jax.jit(lambda key: init_all(key)[1])(
                jax.random.PRNGKey(g.seed))
        else:
            new_params, model_state = jax.jit(init_all)(jax.random.PRNGKey(g.seed))
        self._tx = self._build_tx(new_params)
        self.train_state = TrainState(
            params=new_params, model_state=model_state,
            opt_state=self._tx.init(new_params), step=jnp.zeros((), jnp.int32))
        self._jit_cache.clear()
        self._rnn_carries = None  # stale hidden state must not cross inits
        return self

    def _build_tx(self, params) -> optax.GradientTransformation:
        g = self.conf.global_conf
        default_updater: Updater = g.updater if g.updater is not None else Sgd(0.1)
        transforms, labels = {}, {}
        for n in self.conf.nodes:
            if n.kind != "layer" or n.name not in params:
                continue
            layer = n.obj
            if layer.frozen:
                tx = optax.set_to_zero()
            else:
                upd = layer.updater if layer.updater is not None else default_updater
                chain = []
                gn = gradient_normalization_transform(
                    g.gradient_normalization, g.gradient_normalization_threshold)
                if gn is not None:
                    chain.append(gn)
                chain.append(upd.make())
                wd = layer.weight_decay if layer.weight_decay is not None else g.weight_decay
                if wd:
                    from deeplearning4j_tpu.train.updaters import decoupled_weight_decay
                    reg = set(layer.regularizable_params())
                    chain.append(decoupled_weight_decay(
                        wd, upd._lr(), mask=lambda p, rk=reg: _mask_keys(p, rk)))
                tx = optax.chain(*chain) if len(chain) > 1 else chain[0]
            transforms[n.name] = tx
            labels[n.name] = jax.tree.map(lambda _: n.name, params[n.name])
        return optax.multi_transform(transforms, labels)

    # --------------------------------------------------------------- forward
    def _exec_node(self, i: int, name: str, acts, last_inputs, new_state,
                   params, model_state, *, training, rng, masks, carries,
                   output_set):
        """Execute one topo node, mutating acts/last_inputs/new_state.
        Returns the (possibly replaced) carries dict."""
        node = self.conf.node(name)
        ins = [acts[k] for k in node.inputs]
        if node.kind == "vertex":
            acts[name] = node.obj.forward(*ins)
            return carries
        x = ins[0]
        pp = getattr(node, "inputs_preprocessor", None)
        if pp is not None:
            x = pp.pre_process(x)
        mask = None if masks is None else masks.get(name)
        lrng = jax.random.fold_in(rng, i) if rng is not None else None
        if training and getattr(node.obj, "weight_noise", None) is not None:
            from deeplearning4j_tpu.nn.constraints import apply_weight_noise
            params = dict(params)
            params[name] = apply_weight_noise(
                node.obj, params.get(name, {}),
                None if lrng is None else jax.random.fold_in(lrng, 7919))
        if name in output_set and hasattr(node.obj, "compute_loss"):
            # apply input dropout ONCE; loss and forward share the result
            x = node.obj._apply_input_dropout(x, node.obj._g, training, lrng)
            last_inputs[name] = x
            acts[name] = node.obj.activate(params.get(name, {}), x)
            return carries
        last_inputs[name] = x
        if carries is not None and isinstance(node.obj, BaseRecurrentLayer):
            x = node.obj._apply_input_dropout(x, node.obj._g, training, lrng)
            y, c_new = node.obj.forward_with_carry(
                params.get(name, {}), carries[name], x,
                training=training, rng=lrng, mask=mask)
            carries = dict(carries)
            carries[name] = c_new
        else:
            y, s_new = node.obj.forward(params.get(name, {}),
                                        model_state.get(name, {}),
                                        x, training=training, rng=lrng, mask=mask)
            if model_state.get(name):
                new_state[name] = s_new
        acts[name] = y
        return carries

    def _remat_segments(self) -> List[List[str]]:
        """Partition ``topo_order`` into segments at single-tensor cut points
        (DAG articulations: the only value still live is the node itself).
        For ResNet-style graphs the cuts land exactly on the residual-block
        outputs, so ``jax.checkpoint`` around a segment saves ONE boundary
        activation instead of every intra-block tensor. The tail segment
        (containing the output/loss layers) is never rematerialized."""
        if self._remat_segs is not None:
            return self._remat_segs
        topo = self.conf.topo_order
        node_inputs = {n: list(self.conf.node(n).inputs) for n in topo}
        last_use: Dict[str, int] = {}
        for idx, n in enumerate(topo):
            for t in node_inputs[n]:
                last_use[t] = idx
        inf = len(topo) + 1
        for o in self.conf.outputs:  # outputs + their inputs feed the loss
            last_use[o] = inf
            for t in node_inputs.get(o, []):
                last_use[t] = inf
        live: set = {t for t in self.conf.inputs if last_use.get(t, -1) >= 0}
        segs: List[List[str]] = []
        cur: List[str] = []
        for idx, n in enumerate(topo):
            cur.append(n)
            live = {t for t in live if last_use.get(t, -1) > idx}
            if last_use.get(n, -1) > idx:
                live.add(n)
            if live == {n} and idx < len(topo) - 1:
                segs.append(cur)
                cur = []
        if cur:
            segs.append(cur)
        self._remat_segs = segs
        return segs

    def _forward_all(self, params, model_state, inputs: Dict[str, jax.Array], *,
                     training: bool, rng, masks: Optional[Dict[str, Any]] = None,
                     carries: Optional[Dict[str, Any]] = None):
        """Execute the DAG; returns (activations dict incl. pre-output inputs,
        new model state[, new carries when ``carries`` given]) — the carry
        path is the graph analog of the reference's ``rnnTimeStep`` stateful
        inference on ``ComputationGraph``."""
        env = get_environment()
        cdt = env.compute_dtype
        params = cast_floating(params, cdt)
        acts: Dict[str, Any] = {}
        for name, x in inputs.items():
            if jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != cdt:
                x = x.astype(cdt)
            acts[name] = x
        last_inputs: Dict[str, Any] = {}
        new_state = dict(model_state)
        output_set = set(self.conf.outputs)

        use_remat = (env.remat_segments and training and carries is None
                     and masks is None)
        if use_remat:
            return self._forward_remat(params, model_state, acts, last_inputs,
                                       new_state, rng, output_set)

        for i, name in enumerate(self.conf.topo_order):
            carries = self._exec_node(
                i, name, acts, last_inputs, new_state, params, model_state,
                training=training, rng=rng, masks=masks, carries=carries,
                output_set=output_set)
        if carries is not None:
            return acts, last_inputs, new_state, carries
        return acts, last_inputs, new_state

    def _forward_remat(self, params, model_state, acts, last_inputs,
                       new_state, rng, output_set):
        """Training forward with per-segment rematerialization (see
        :meth:`_remat_segments`; the HBM-vs-FLOPs trade the reference's
        workspace system makes by hand, made by the compiler here)."""
        topo = self.conf.topo_order
        base = {n: i for i, n in enumerate(topo)}
        segs = self._remat_segments()
        for k, seg in enumerate(segs):
            is_tail = (k == len(segs) - 1)
            seg_set = set(seg)
            ext = sorted({t for n in seg for t in
                          (self.conf.node(n).inputs or [])
                          if t not in seg_set})
            if is_tail or len(seg) < 2:
                for n in seg:
                    self._exec_node(
                        base[n], n, acts, last_inputs, new_state, params,
                        model_state, training=True, rng=rng, masks=None,
                        carries=None, output_set=output_set)
                continue

            seg_params = {n: params[n] for n in seg if n in params}
            seg_mstate = {n: model_state[n] for n in seg if n in model_state}
            out_name = seg[-1]

            def seg_fn(seg_params, seg_mstate, ext_acts, rng, _seg=seg,
                       _ext=ext, _out=out_name):
                a = dict(zip(_ext, ext_acts))
                li: Dict[str, Any] = {}
                ns = dict(seg_mstate)
                for n in _seg:
                    self._exec_node(
                        base[n], n, a, li, ns, seg_params, seg_mstate,
                        training=True, rng=rng, masks=None, carries=None,
                        output_set=output_set)
                return a[_out], ns

            y, seg_new_state = jax.checkpoint(seg_fn)(
                seg_params, seg_mstate, tuple(acts[t] for t in ext), rng)
            acts[out_name] = y
            for n, s in seg_new_state.items():
                if model_state.get(n):
                    new_state[n] = s
        return acts, last_inputs, new_state

    def _loss(self, params, model_state, inputs, labels, rng, masks=None,
              training: bool = True, carries=None):
        if carries is not None:
            acts, last_inputs, new_state, new_carries = self._forward_all(
                params, model_state, inputs, training=training, rng=rng,
                masks=masks, carries=carries)
        else:
            acts, last_inputs, new_state = self._forward_all(
                params, model_state, inputs, training=training, rng=rng,
                masks=masks)
            new_carries = None
        total = jnp.zeros((), jnp.float32)
        for out_name, y in zip(self.conf.outputs, labels):
            node = self.conf.node(out_name)
            layer = node.obj
            if not hasattr(layer, "compute_loss"):
                raise ValueError(f"Output node {out_name!r} is not an output layer")
            mask = None if masks is None else masks.get(out_name)
            out_p = cast_floating(params.get(out_name, {}),
                                  get_environment().compute_dtype)
            if training and getattr(layer, "weight_noise", None) is not None \
                    and rng is not None:
                # mirror _exec_node's noise keys so loss and activations
                # agree on the perturbed weights
                from deeplearning4j_tpu.nn.constraints import apply_weight_noise
                i_node = self.conf.topo_order.index(out_name)
                lrng = jax.random.fold_in(rng, i_node)
                out_p = apply_weight_noise(layer, out_p,
                                           jax.random.fold_in(lrng, 7919))
            total = total + layer.compute_loss(
                out_p, last_inputs[out_name], y, mask=mask,
                state=model_state.get(out_name, {}))
            if training and hasattr(layer, "update_state_with_labels"):
                new_state = dict(new_state)
                new_state[out_name] = layer.update_state_with_labels(
                    model_state.get(out_name, {}),
                    jax.lax.stop_gradient(last_inputs[out_name]), y)
        total = total + self._reg_score(params)
        # layer auxiliary losses (e.g. MoE load balancing) — training only
        if training:
            for s2 in new_state.values():
                if isinstance(s2, dict) and "_aux_loss" in s2:
                    total = total + s2["_aux_loss"]
        return total, (new_state, new_carries)

    def _reg_score(self, params):
        g = self.conf.global_conf
        total = jnp.zeros((), jnp.float32)
        for n in self.conf.nodes:
            if n.kind != "layer" or n.name not in params:
                continue
            layer = n.obj
            l1 = layer.l1 if layer.l1 is not None else g.l1
            l2 = layer.l2 if layer.l2 is not None else g.l2
            if not l1 and not l2:
                continue
            reg_keys = set(layer.regularizable_params())
            for path, w in jax.tree_util.tree_flatten_with_path(params[n.name])[0]:
                if any(getattr(p, "key", None) in reg_keys for p in path):
                    if l1:
                        total = total + l1 * jnp.sum(jnp.abs(w))
                    if l2:
                        total = total + 0.5 * l2 * jnp.sum(w * w)
        return total

    # ------------------------------------------------------------ train/fit
    def _apply_constraints(self, params):
        """Post-update projections (reference applyConstraints)."""
        from deeplearning4j_tpu.nn.constraints import apply_layer_constraints
        layer_nodes = [n for n in self.conf.topo_order
                       if self.conf.node(n).kind == "layer"]
        if not any(getattr(self.conf.node(n).obj, "constraints", None)
                   or getattr(self.conf.node(n).obj, "bias_constraints", None)
                   for n in layer_nodes):
            return params
        out = dict(params)
        for n in layer_nodes:
            if n in out:
                out[n] = apply_layer_constraints(self.conf.node(n).obj, out[n])
        return out

    def _train_step_fn(self):
        def step(ts: TrainState, inputs, labels, rng, masks):
            (loss, (new_state, _)), grads = jax.value_and_grad(
                self._loss, has_aux=True)(
                ts.params, ts.model_state, inputs, labels, rng, masks)
            updates, new_opt = self._tx.update(grads, ts.opt_state, ts.params)
            new_params = self._apply_constraints(
                optax.apply_updates(ts.params, updates))
            return TrainState(params=new_params, model_state=new_state,
                              opt_state=new_opt, step=ts.step + 1), loss

        return step

    def _make_train_step(self):
        return jax.jit(self._train_step_fn(), donate_argnums=(0,))

    def _make_packed_train_step(self):
        """Train step with flat-packed small leaves at the jit boundary
        (see :mod:`deeplearning4j_tpu.runtime.state_packing`): same math,
        bit-identical results, ~4x fewer buffer handles per dispatch."""
        from deeplearning4j_tpu.runtime.state_packing import LeafPacker
        packer = LeafPacker(self.train_state)
        raw = self._train_step_fn()

        def packed_step(pts, inputs, labels, rng, masks):
            new_ts, loss = raw(packer.unpack(pts), inputs, labels, rng, masks)
            return packer.pack(new_ts), loss

        return jax.jit(packed_step, donate_argnums=(0,)), packer

    def _make_tbptt_step(self):
        """Train step carrying recurrent state across truncated chunks
        (reference: tBPTT on ComputationGraph)."""
        def step(ts: TrainState, carries, inputs, labels, rng, masks):
            (loss, (new_state, new_carries)), grads = jax.value_and_grad(
                self._loss, has_aux=True)(
                ts.params, ts.model_state, inputs, labels, rng, masks,
                True, carries)
            updates, new_opt = self._tx.update(grads, ts.opt_state, ts.params)
            new_params = optax.apply_updates(ts.params, updates)
            new_carries = jax.tree.map(jax.lax.stop_gradient, new_carries)
            return (TrainState(params=new_params, model_state=new_state,
                               opt_state=new_opt, step=ts.step + 1),
                    new_carries, loss)

        return jax.jit(step, donate_argnums=(0, 1))

    def _jitted(self, name, factory):
        # remat is read at TRACE time, so flipping env.set_remat() must
        # invalidate previously jitted steps — key the cache on the flag.
        key = f"{name}@remat={get_environment().remat_segments}"
        if key not in self._jit_cache:
            self._jit_cache[key] = factory()
        return self._jit_cache[key]

    def _packed_cache_key(self) -> str:
        return f"packed_train_step@remat={get_environment().remat_segments}"

    def _jitted_packed(self):
        # keyed directly by _packed_cache_key so the invalidation path in
        # PackedStepLoop.step pops the SAME key this populates
        key = self._packed_cache_key()
        if key not in self._jit_cache:
            self._jit_cache[key] = self._make_packed_train_step()
        return self._jit_cache[key]

    def _jitted_packed_unrolled(self, k: int):
        """K same-shape batches per device dispatch (env.dispatch_unroll);
        shares the single-step packer (see MultiLayerNetwork)."""
        key = f"{self._packed_cache_key()}@unroll={k}"
        if key not in self._jit_cache:
            from deeplearning4j_tpu.runtime.state_packing import (
                make_unrolled_packed_step)
            _, packer = self._jitted_packed()
            self._jit_cache[key] = make_unrolled_packed_step(
                self._train_step_fn(), packer, k)
        return self._jit_cache[key]

    def _coerce_batch(self, batch) -> Tuple[Dict[str, Any], List[Any], Optional[Dict]]:
        from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
        if isinstance(batch, MultiDataSet):
            inputs = {n: jnp.asarray(f) for n, f in zip(self.conf.inputs, batch.features)}
            labels = [jnp.asarray(l) for l in batch.labels]
            masks = None
            if batch.labels_masks is not None:
                masks = {o: (None if m is None else jnp.asarray(m))
                         for o, m in zip(self.conf.outputs, batch.labels_masks)}
            return inputs, labels, masks
        ds: DataSet = batch
        inputs = {self.conf.inputs[0]: jnp.asarray(ds.features)}
        labels = [jnp.asarray(ds.labels)]
        masks = None
        if ds.labels_mask is not None:
            masks = {self.conf.outputs[0]: jnp.asarray(ds.labels_mask)}
        return inputs, labels, masks

    def fit(self, data, labels=None, epochs: int = 1,
            prefetch_buffer: int = 0, profiler=None) -> "ComputationGraph":
        """``prefetch_buffer > 0`` stages coerced batches on-device ahead of
        the step (``train.prefetch.DevicePrefetcher``; trajectory
        bit-identical to the synchronous loop); ``profiler`` takes a
        :class:`~deeplearning4j_tpu.train.profiler.TrainingProfiler`."""
        if self.train_state is None:
            self.init()
        if labels is not None:
            from deeplearning4j_tpu.data.dataset import DataSet
            from deeplearning4j_tpu.data.iterators import ListDataSetIterator
            iterator = ListDataSetIterator(
                [DataSet(np.asarray(data), np.asarray(labels))], batch_size=len(data))
        else:
            iterator = data
        from deeplearning4j_tpu.runtime.state_packing import (GroupedDispatch,
                                                               PackedStepLoop)
        from deeplearning4j_tpu.train.prefetch import (AsyncLossDelivery,
                                                       stateless_listeners)
        ploop = PackedStepLoop.for_network(self)
        if profiler is not None:
            profiler.start()

        def deliver(_n, loss):
            self._score = loss
            self._iteration += 1
            for lst in self._listeners:
                lst.iteration_done(self, self._iteration, self._epoch, loss)

        # async loss readback (see MultiLayerNetwork._fit_epochs): listener
        # delivery moves to a completion thread when every listener is
        # stateless — same callbacks, same order, no dispatch stall; no
        # listeners and no profiler = deliver inline, no thread
        adel = (AsyncLossDelivery(deliver, profiler=profiler)
                if (self._listeners or profiler is not None)
                and stateless_listeners(self) else None)
        # nothing but the loss crosses into the delivery queue — queued step
        # args would pin full device batches for up to max_pending steps
        sink = adel.submit if adel is not None else deliver
        gd = GroupedDispatch(
            # with a state-reading listener, packing is off and batches must
            # dispatch one at a time so iteration_done sees fresh state
            unroll=(get_environment().dispatch_unroll if ploop.enabled else 1),
            compatible=_cg_group_compatible,
            run_single=lambda a: ploop.step(*a)[0],
            run_group=ploop.step_group,
            deliver=lambda args, loss: sink(None, loss))
        try:
            try:
                self._fit_epochs(
                    iterator, int(epochs), ploop, gd,
                    drain=(adel.flush if adel is not None else (lambda: None)),
                    prefetch_buffer=int(prefetch_buffer), profiler=profiler)
            finally:
                gd.drain_on_error()
                if adel is not None:
                    adel.shutdown()  # never raises; original errors win
        finally:
            # any exit path (incl. KeyboardInterrupt / iterator errors) must
            # leave train_state reflecting every completed step
            ploop.sync(release=True)
            if profiler is not None:
                profiler.stop()
        if adel is not None:
            adel.raise_pending()
        return self

    def _fit_epochs(self, iterator, epochs: int, ploop, gd,
                    drain=lambda: None, prefetch_buffer: int = 0,
                    profiler=None) -> None:
        from deeplearning4j_tpu.train.prefetch import batch_source
        from deeplearning4j_tpu.train.profiler import submit_timed
        for _ in range(epochs):
            for lst in self._listeners:
                lst.on_epoch_start(self, self._epoch)
            src = batch_source(iterator, self._coerce_batch,
                               prefetch_buffer, profiler)
            try:
                for inputs, labels_, masks in src:
                    algo = self.conf.global_conf.optimization_algo
                    if self.conf.tbptt_fwd_length and any(
                            is_sequence_array(v) for v in inputs.values()):
                        if algo != "STOCHASTIC_GRADIENT_DESCENT":
                            raise NotImplementedError(
                                "tBPTT training with optimization_algo="
                                f"{algo!r} is not supported; use SGD or full-"
                                "sequence BPTT")
                        gd.flush()
                        drain()  # tBPTT notifies listeners inline (ordered)
                        ploop.sync(release=True)  # tBPTT mutates train_state
                        self._fit_tbptt(inputs, labels_, masks)
                        continue
                    if algo != "STOCHASTIC_GRADIENT_DESCENT":
                        from deeplearning4j_tpu.train.solvers import (
                            graph_solver_fit_batch)
                        gd.flush()
                        ploop.sync(release=True)  # solver mutates train_state
                        loss = graph_solver_fit_batch(self, inputs, labels_, masks)
                        gd._deliver((inputs, labels_, None, masks), loss)
                        continue
                    submit_timed(
                        gd, (inputs, labels_, self.rng.next_key(), masks),
                        profiler)
            finally:
                src.close()
            gd.flush()
            drain()  # on_epoch_end must observe every iteration_done
            # no epoch-end sync: packing only runs when every listener is
            # stateless, so nothing reads train_state until fit() returns
            for lst in self._listeners:
                lst.on_epoch_end(self, self._epoch)
            self._epoch += 1

    def _fit_tbptt(self, inputs, labels_, masks):
        """Chunk the time axis into tbptt-length windows, carrying hidden
        state between them (reference: tBPTT on ComputationGraph)."""
        L = int(self.conf.tbptt_fwd_length)
        T = max(seq_length(v) for v in inputs.values() if is_sequence_array(v))
        first = next(iter(inputs.values()))
        dt = carry_dtype(first, get_environment().compute_dtype)
        carries = {
            n.name: n.obj.init_carry(first.shape[0], dt)
            for n in self.conf.nodes
            if n.kind == "layer" and isinstance(n.obj, BaseRecurrentLayer)}
        step_fn = self._jitted("tbptt_step", self._make_tbptt_step)
        for t0 in range(0, T, L):
            ci = {k: slice_time(v, t0, L) for k, v in inputs.items()}
            cl = [y[:, t0:t0 + L] if hasattr(y, "ndim") and y.ndim == 3 else y
                  for y in labels_]
            cm = None if masks is None else {
                k: (m[:, t0:t0 + L] if hasattr(m, "ndim") and m.ndim >= 2
                    and m.shape[1] == T else m)
                for k, m in masks.items()}
            rng = self.rng.next_key()
            self.train_state, carries, loss = step_fn(
                self.train_state, carries, ci, cl, rng, cm)
            self._score = loss
            self._iteration += 1
            for lst in self._listeners:
                lst.iteration_done(self, self._iteration, self._epoch, loss)

    # ------------------------------------------------------------- inference
    def output(self, *xs, training: bool = False):
        """Forward; returns list of output arrays (single array if one output)."""
        if self.train_state is None:
            self.init()
        inputs = {n: jnp.asarray(x) for n, x in zip(self.conf.inputs, xs)}

        def fwd(params, model_state, inputs_):
            acts, _, _ = self._forward_all(params, model_state, inputs_,
                                           training=False, rng=None)
            return [acts[o] for o in self.conf.outputs]

        fn = self._jitted("output", lambda: jax.jit(fwd))
        outs = fn(self.train_state.params, self.train_state.model_state, inputs)
        return outs[0] if len(outs) == 1 else outs

    def _coerce_inputs(self, inputs) -> Dict[str, jax.Array]:
        """Accept a dict, a single array (single-input graph), or a
        list/tuple of arrays zipped element-wise against ``conf.inputs``."""
        if isinstance(inputs, dict):
            return {k: jnp.asarray(v) for k, v in inputs.items()}
        if isinstance(inputs, (list, tuple)):
            if len(inputs) != len(self.conf.inputs):
                raise ValueError(
                    f"graph has {len(self.conf.inputs)} inputs "
                    f"{self.conf.inputs}; got {len(inputs)} arrays")
            return {n: jnp.asarray(v)
                    for n, v in zip(self.conf.inputs, inputs)}
        return {self.conf.inputs[0]: jnp.asarray(inputs)}

    # --------------------------------------------------- external errors
    def backprop_gradient(self, inputs, epsilons):
        """Reference ``ComputationGraph`` external-errors mode: given
        dL/dOutput for each graph output (produced OUTSIDE the graph), return
        ``(param_gradients, {input_name: dL/dInput})`` — one jitted vjp."""
        if self.train_state is None:
            self.init()
        inputs = self._coerce_inputs(inputs)
        if not isinstance(epsilons, (list, tuple)):
            epsilons = [epsilons]
        epsilons = [jnp.asarray(e) for e in epsilons]

        def fn(params, model_state, inputs_, eps):
            def f(p, ins):
                acts, _, new_state = self._forward_all(
                    p, model_state, ins, training=True, rng=None)
                return [acts[o] for o in self.conf.outputs], new_state
            outs, vjp, _ = jax.vjp(f, params, inputs_, has_aux=True)
            gp, gin = vjp([e.astype(o.dtype) for e, o in zip(eps, outs)])
            return gp, gin

        fn = self._jitted("backprop_external", lambda: jax.jit(fn))
        return fn(self.train_state.params, self.train_state.model_state,
                  inputs, epsilons)

    def fit_external(self, inputs, epsilons):
        """External-errors TRAINING step on the graph: backprop the provided
        output cotangents and apply the configured updater (one jitted
        donated step). Returns {input_name: dL/dInput}."""
        if self.train_state is None:
            self.init()
        inputs = self._coerce_inputs(inputs)
        if not isinstance(epsilons, (list, tuple)):
            epsilons = [epsilons]
        epsilons = [jnp.asarray(e) for e in epsilons]

        def make():
            def step(ts: TrainState, inputs_, eps, rng):
                def f(p, ins):
                    acts, _, new_state = self._forward_all(
                        p, ts.model_state, ins, training=True, rng=rng)
                    return [acts[o] for o in self.conf.outputs], new_state
                outs, vjp, new_state = jax.vjp(f, ts.params, inputs_,
                                               has_aux=True)
                gp, gin = vjp([e.astype(o.dtype) for e, o in zip(eps, outs)])
                updates, new_opt = self._tx.update(gp, ts.opt_state, ts.params)
                new_params = optax.apply_updates(ts.params, updates)
                return TrainState(params=new_params, model_state=new_state,
                                  opt_state=new_opt, step=ts.step + 1), gin
            return jax.jit(step, donate_argnums=(0,))

        fn = self._jitted("fit_external", make)
        self.train_state, gin = fn(self.train_state, inputs, epsilons,
                                   self.rng.next_key())
        self._iteration += 1
        return gin

    def _rnn_step_fn(self):
        """The jitted ``(params, model_state, inputs, carries) ->
        (outs, new_carries)`` program behind :meth:`rnn_time_step` and
        :meth:`rnn_time_step_external` — one shared cache key, so the
        stateful and pure-functional paths compile once and stay
        bit-identical at equal program shape."""
        def make():
            def fwd(params, model_state, inputs_, carries):
                acts, _, _, new_carries = self._forward_all(
                    params, model_state, inputs_, training=False, rng=None,
                    carries=carries)
                return [acts[o] for o in self.conf.outputs], new_carries
            return jax.jit(fwd)

        return self._jitted("rnn_time_step", make)

    def _rnn_zero_carries(self, batch: int, carry_dt):
        return {n.name: n.obj.init_carry(batch, carry_dt)
                for n in self.conf.nodes
                if n.kind == "layer" and isinstance(n.obj, BaseRecurrentLayer)}

    def rnn_time_step(self, *xs):
        """Stateful step-by-step inference (reference
        ``ComputationGraph.rnnTimeStep``): hidden state carries across calls
        until :meth:`rnn_clear_previous_state`."""
        if self.train_state is None:
            self.init()
        inputs = {n: jnp.asarray(x) for n, x in zip(self.conf.inputs, xs)}
        first = next(iter(inputs.values()))
        carry_dt = carry_dtype(first, get_environment().compute_dtype)
        if getattr(self, "_rnn_carries", None) is None:
            self._rnn_carries = self._rnn_zero_carries(first.shape[0],
                                                       carry_dt)
        fn = self._rnn_step_fn()
        outs, self._rnn_carries = fn(self.train_state.params,
                                     self.train_state.model_state, inputs,
                                     self._rnn_carries)
        return outs[0] if len(outs) == 1 else outs

    def rnn_clear_previous_state(self) -> None:
        self._rnn_carries = None

    def rnn_get_state(self):
        """Serializable copy of the stored recurrent state (reference
        ``rnnGetPreviousState``): numpy-leaved tree, dtype-stable, ``None``
        when no state is stored. Bit-exact round trip through
        :meth:`rnn_set_state`."""
        if getattr(self, "_rnn_carries", None) is None:
            return None
        return jax.tree.map(np.asarray, self._rnn_carries)

    def rnn_set_state(self, state) -> None:
        """Install a state captured with :meth:`rnn_get_state` (reference
        ``rnnSetPreviousState``); ``None`` clears."""
        self._rnn_carries = (None if state is None
                             else jax.tree.map(jnp.asarray, state))

    def rnn_zero_state(self, batch: int, like=None):
        """Fresh zero recurrent state for a ``batch``-row stream — the tree
        :meth:`rnn_time_step` would lazily create on first call."""
        if self.train_state is None:
            self.init()
        dt = (get_environment().compute_dtype if like is None else
              carry_dtype(jnp.asarray(like), get_environment().compute_dtype))
        return self._rnn_zero_carries(batch, dt)

    def rnn_time_step_external(self, *xs, state):
        """Pure-functional ``rnnTimeStep`` on the graph: advance ``state``
        (or ``None`` for a fresh stream) by one chunk without touching the
        stored state; returns ``(out, new_state)``. Shares
        :meth:`rnn_time_step`'s compiled program."""
        if self.train_state is None:
            self.init()
        inputs = {n: jnp.asarray(x) for n, x in zip(self.conf.inputs, xs)}
        first = next(iter(inputs.values()))
        if state is None:
            state = self._rnn_zero_carries(
                first.shape[0],
                carry_dtype(first, get_environment().compute_dtype))
        fn = self._rnn_step_fn()
        outs, new_state = fn(self.train_state.params,
                             self.train_state.model_state, inputs, state)
        return (outs[0] if len(outs) == 1 else outs), new_state

    def score(self, dataset=None) -> float:
        if dataset is None:
            return float(self._score)
        inputs, labels, masks = self._coerce_batch(dataset)

        def score_fn(params, model_state, i_, l_, m_):
            loss, _ = self._loss(params, model_state, i_, l_, None, m_,
                                 training=False)
            return loss

        fn = self._jitted("score", lambda: jax.jit(score_fn))
        return float(fn(self.train_state.params, self.train_state.model_state,
                        inputs, labels, masks))

    def evaluate(self, iterator, output_index: int = 0):
        """Classification eval on one output (reference
        ``evaluate(DataSetIterator)``); handles multi-input MultiDataSets."""
        from deeplearning4j_tpu.evaluation.evaluation import Evaluation
        ev = Evaluation()
        iterator.reset()
        for batch in iterator:
            inputs, labels, _ = self._coerce_batch(batch)
            outs = self.output(*[inputs[n] for n in self.conf.inputs])
            if isinstance(outs, list):
                outs = outs[output_index]
            ev.eval(np.asarray(labels[output_index]), np.asarray(outs))
        return ev

    # -------------------------------------------------------------- plumbing
    def set_listeners(self, *listeners: TrainingListener) -> None:
        self._listeners = list(listeners)

    def get_listeners(self):
        return list(self._listeners)

    def add_listeners(self, *listeners: TrainingListener) -> None:
        self._listeners.extend(listeners)

    def clone(self) -> "ComputationGraph":
        net = ComputationGraph(
            ComputationGraphConfiguration.from_dict(self.conf.to_dict()))
        if self.train_state is not None:
            net.init(params=jax.tree.map(jnp.copy, self.train_state.params))
            import dataclasses as _dc
            net.train_state = _dc.replace(
                net.train_state,
                model_state=jax.tree.map(jnp.copy, self.train_state.model_state))
        return net

    def params(self):
        return self.train_state.params if self.train_state else None

    def set_params(self, params) -> None:
        if self.train_state is None:
            self.init(params=params)
        else:
            self.train_state = dataclasses.replace(self.train_state, params=params)

    def num_params(self) -> int:
        if self.train_state is None:
            return 0
        return int(sum(np.prod(p.shape) for p in jax.tree.leaves(self.train_state.params)))

    def save(self, path: str, save_updater: bool = True) -> None:
        from deeplearning4j_tpu.models.serializer import ModelSerializer
        ModelSerializer.write_model(self, path, save_updater=save_updater)

    @staticmethod
    def load(path: str, load_updater: bool = True) -> "ComputationGraph":
        from deeplearning4j_tpu.models.serializer import ModelSerializer
        return ModelSerializer.restore_computation_graph(path, load_updater=load_updater)
