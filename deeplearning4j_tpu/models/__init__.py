"""Networks: MultiLayerNetwork (linear stack), ComputationGraph (DAG),
ModelSerializer (single-file archives).

Rebuild of upstream ``org.deeplearning4j.nn.multilayer.MultiLayerNetwork``,
``org.deeplearning4j.nn.graph.ComputationGraph`` and
``org.deeplearning4j.util.ModelSerializer`` — re-architected graph-first: the
network composes all layers into ONE jitted XLA program per (train / inference)
entry point instead of dispatching per-op like the reference.
"""

from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork, TrainState
from deeplearning4j_tpu.models.computation_graph import ComputationGraph, GraphBuilder
from deeplearning4j_tpu.models.serializer import ModelSerializer
from deeplearning4j_tpu.models.transfer_learning import (
    FineTuneConfiguration,
    TransferLearning,
)

__all__ = ["MultiLayerNetwork", "TrainState", "ComputationGraph", "GraphBuilder",
           "ModelSerializer", "TransferLearning", "FineTuneConfiguration"]
