"""INDArray / Nd4j facade: the reference's user-facing tensor API.

Rebuild of ``org.nd4j.linalg.api.ndarray.INDArray`` + the ``Nd4j`` static
factory (upstream ``org.nd4j.linalg.factory.Nd4j``) as a thin facade over
jax.numpy. The reference's INDArray is a mutable buffer with views; on TPU
the idiomatic contract is immutability inside compiled programs, so:

- "in-place" methods (``addi``, ``muli``, ``assign`` …) mutate the *wrapper*
  (rebind its buffer), giving the reference's call-site ergonomics while the
  underlying arrays stay functional — safe to pass into jit;
- slices/views are copies (functional semantics). Code that mutated a DL4J
  view must use ``put``/``put_scalar``, which rebind via lax scatter.

Every op stays a jax op, so INDArray code composes with jit/grad/vmap — the
facade never forces a host sync except explicit ``.item()``/``.numpy()``.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np


# ----------------------------------------------------------------- indexing
class NDArrayIndex:
    """Reference ``org.nd4j.linalg.indexing.NDArrayIndex``."""

    def __init__(self, sel):
        self.sel = sel

    @staticmethod
    def all() -> "NDArrayIndex":
        return NDArrayIndex(slice(None))

    @staticmethod
    def point(i: int) -> "NDArrayIndex":
        return NDArrayIndex(int(i))

    @staticmethod
    def interval(start: int, end: int, step: int = 1) -> "NDArrayIndex":
        return NDArrayIndex(slice(int(start), int(end), int(step)))

    @staticmethod
    def indices(*idx: int) -> "NDArrayIndex":
        return NDArrayIndex(np.asarray(idx, np.int32))


def _unwrap(x):
    return x.array if isinstance(x, INDArray) else x


def _sel_tuple(indices) -> tuple:
    return tuple(i.sel if isinstance(i, NDArrayIndex) else i for i in indices)


class INDArray:
    """Wrapper around a jax array with the reference's method surface."""

    __slots__ = ("array",)
    __array_priority__ = 100  # numpy defers binary ops to us

    def __init__(self, array):
        self.array = jnp.asarray(array)

    # ---- structure ----
    def shape(self) -> Tuple[int, ...]:
        return tuple(self.array.shape)

    def rank(self) -> int:
        return self.array.ndim

    def length(self) -> int:
        return int(self.array.size)

    def size(self, dim: int) -> int:
        return int(self.array.shape[dim])

    def data_type(self):
        return self.array.dtype

    def rows(self) -> int:
        return self.size(0)

    def columns(self) -> int:
        return self.size(1)

    def is_vector(self) -> bool:
        return self.array.ndim == 1 or (
            self.array.ndim == 2 and 1 in self.array.shape)

    def is_matrix(self) -> bool:
        return self.array.ndim == 2

    def is_scalar(self) -> bool:
        return self.array.ndim == 0 or self.array.size == 1

    # ---- reshape family (functional: return new INDArray) ----
    def reshape(self, *shape) -> "INDArray":
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return INDArray(self.array.reshape(shape))

    def ravel(self) -> "INDArray":
        return INDArray(self.array.reshape(-1))

    def transpose(self) -> "INDArray":
        return INDArray(self.array.T)

    def permute(self, *axes) -> "INDArray":
        return INDArray(jnp.transpose(self.array, axes))

    def swap_axes(self, a: int, b: int) -> "INDArray":
        return INDArray(jnp.swapaxes(self.array, a, b))

    def broadcast(self, *shape) -> "INDArray":
        return INDArray(jnp.broadcast_to(self.array, shape))

    def repeat(self, dim: int, n: int) -> "INDArray":
        return INDArray(jnp.repeat(self.array, n, axis=dim))

    def dup(self) -> "INDArray":
        return INDArray(self.array)  # immutable: sharing IS a copy

    def cast_to(self, dtype) -> "INDArray":
        return INDArray(self.array.astype(dtype))

    # ---- elementwise arithmetic: pure + "in-place" (rebind) variants ----
    def _bin(self, other, fn) -> "INDArray":
        return INDArray(fn(self.array, _unwrap(other)))

    def add(self, o) -> "INDArray":
        return self._bin(o, jnp.add)

    def sub(self, o) -> "INDArray":
        return self._bin(o, jnp.subtract)

    def mul(self, o) -> "INDArray":
        return self._bin(o, jnp.multiply)

    def div(self, o) -> "INDArray":
        return self._bin(o, jnp.divide)

    def rsub(self, o) -> "INDArray":
        return INDArray(_unwrap(o) - self.array)

    def rdiv(self, o) -> "INDArray":
        return INDArray(_unwrap(o) / self.array)

    def neg(self) -> "INDArray":
        return INDArray(-self.array)

    def _i(self, result: "INDArray") -> "INDArray":
        self.array = result.array
        return self

    def addi(self, o) -> "INDArray":
        return self._i(self.add(o))

    def subi(self, o) -> "INDArray":
        return self._i(self.sub(o))

    def muli(self, o) -> "INDArray":
        return self._i(self.mul(o))

    def divi(self, o) -> "INDArray":
        return self._i(self.div(o))

    def rsubi(self, o) -> "INDArray":
        return self._i(self.rsub(o))

    def rdivi(self, o) -> "INDArray":
        return self._i(self.rdiv(o))

    def negi(self) -> "INDArray":
        return self._i(self.neg())

    def assign(self, o) -> "INDArray":
        self.array = jnp.broadcast_to(jnp.asarray(_unwrap(o)), self.array.shape)
        return self

    # python operators
    __add__ = add
    __sub__ = sub
    __mul__ = mul
    __truediv__ = div
    __radd__ = add
    __rmul__ = mul
    __rsub__ = rsub
    __rtruediv__ = rdiv
    __neg__ = neg

    def __matmul__(self, o) -> "INDArray":
        return self.mmul(o)

    # ---- matrix ops ----
    def mmul(self, o) -> "INDArray":
        return INDArray(self.array @ _unwrap(o))

    def mmuli(self, o) -> "INDArray":
        return self._i(self.mmul(o))

    # row/column vector broadcasting (reference addRowVector etc.)
    def _rowv(self, o, fn) -> "INDArray":
        return INDArray(fn(self.array, jnp.asarray(_unwrap(o)).reshape(1, -1)))

    def _colv(self, o, fn) -> "INDArray":
        return INDArray(fn(self.array, jnp.asarray(_unwrap(o)).reshape(-1, 1)))

    def add_row_vector(self, o):
        return self._rowv(o, jnp.add)

    def sub_row_vector(self, o):
        return self._rowv(o, jnp.subtract)

    def mul_row_vector(self, o):
        return self._rowv(o, jnp.multiply)

    def div_row_vector(self, o):
        return self._rowv(o, jnp.divide)

    def add_column_vector(self, o):
        return self._colv(o, jnp.add)

    def sub_column_vector(self, o):
        return self._colv(o, jnp.subtract)

    def mul_column_vector(self, o):
        return self._colv(o, jnp.multiply)

    def div_column_vector(self, o):
        return self._colv(o, jnp.divide)

    def addi_row_vector(self, o):
        return self._i(self.add_row_vector(o))

    def muli_row_vector(self, o):
        return self._i(self.mul_row_vector(o))

    # ---- reductions ----
    def _red(self, fn, dims) -> Union["INDArray", float]:
        if not dims:
            return INDArray(fn(self.array))
        return INDArray(fn(self.array, axis=tuple(int(d) for d in dims)))

    def sum(self, *dims):
        return self._red(jnp.sum, dims)

    def mean(self, *dims):
        return self._red(jnp.mean, dims)

    def max(self, *dims):
        return self._red(jnp.max, dims)

    def min(self, *dims):
        return self._red(jnp.min, dims)

    def prod(self, *dims):
        return self._red(jnp.prod, dims)

    def std(self, *dims):
        if not dims:
            n = self.array.size
            return INDArray(jnp.std(self.array, ddof=1 if n > 1 else 0))
        return INDArray(jnp.std(self.array, axis=tuple(dims), ddof=1))

    def var(self, *dims):
        if not dims:
            n = self.array.size
            return INDArray(jnp.var(self.array, ddof=1 if n > 1 else 0))
        return INDArray(jnp.var(self.array, axis=tuple(dims), ddof=1))

    def norm1(self, *dims):
        return self._red(lambda a, **k: jnp.sum(jnp.abs(a), **k), dims)

    def norm2(self, *dims):
        return self._red(lambda a, **k: jnp.sqrt(jnp.sum(a * a, **k)), dims)

    def arg_max(self, *dims) -> "INDArray":
        if not dims:
            return INDArray(jnp.argmax(self.array))
        return INDArray(jnp.argmax(self.array, axis=int(dims[0])))

    def cumsum(self, dim: int) -> "INDArray":
        return INDArray(jnp.cumsum(self.array, axis=dim))

    # ---- comparisons ----
    def lt(self, o):
        return self._bin(o, jnp.less)

    def gt(self, o):
        return self._bin(o, jnp.greater)

    def lte(self, o):
        return self._bin(o, jnp.less_equal)

    def gte(self, o):
        return self._bin(o, jnp.greater_equal)

    def eq(self, o):
        return self._bin(o, jnp.equal)

    def neq(self, o):
        return self._bin(o, jnp.not_equal)

    def equals(self, o) -> bool:
        o = _unwrap(o)
        return bool(self.array.shape == o.shape
                    and jnp.allclose(self.array, o, atol=1e-5))

    # ---- get/put ----
    def get(self, *indices) -> "INDArray":
        return INDArray(self.array[_sel_tuple(indices)])

    def put(self, indices, value) -> "INDArray":
        if not isinstance(indices, (tuple, list)):
            indices = (indices,)
        self.array = self.array.at[_sel_tuple(indices)].set(_unwrap(value))
        return self

    def get_row(self, i: int) -> "INDArray":
        return INDArray(self.array[i])

    def get_column(self, i: int) -> "INDArray":
        return INDArray(self.array[:, i])

    def put_row(self, i: int, row) -> "INDArray":
        self.array = self.array.at[i].set(jnp.asarray(_unwrap(row)).reshape(-1))
        return self

    def put_column(self, i: int, col) -> "INDArray":
        self.array = self.array.at[:, i].set(jnp.asarray(_unwrap(col)).reshape(-1))
        return self

    def get_scalar(self, *idx) -> "INDArray":
        return INDArray(self.array[tuple(int(i) for i in idx)])

    def put_scalar(self, idx, value) -> "INDArray":
        if not isinstance(idx, (tuple, list)):
            idx = (idx,)
        self.array = self.array.at[tuple(int(i) for i in idx)].set(value)
        return self

    def get_double(self, *idx) -> float:
        return float(self.array[tuple(int(i) for i in idx)])

    def slice(self, i: int, dim: int = 0) -> "INDArray":
        return INDArray(jnp.take(self.array, i, axis=dim))

    def tensor_along_dimension(self, index: int, *dims) -> "INDArray":
        """Reference ``tensorAlongDimension``: the ``index``-th sub-tensor
        spanning ``dims``."""
        dims = sorted(d % self.array.ndim for d in dims)
        other = [d for d in range(self.array.ndim) if d not in dims]
        moved = jnp.transpose(self.array, other + dims)
        flat = moved.reshape((-1,) + tuple(self.array.shape[d] for d in dims))
        return INDArray(flat[index])

    def get_rows(self, *rows) -> "INDArray":
        return INDArray(self.array[jnp.asarray([int(r) for r in rows])])

    def get_columns(self, *cols) -> "INDArray":
        return INDArray(self.array[:, jnp.asarray([int(c) for c in cols])])

    # ---- scalar reductions (reference xxxNumber() family) ----
    def sum_number(self) -> float:
        return float(jnp.sum(self.array))

    def mean_number(self) -> float:
        return float(jnp.mean(self.array))

    def max_number(self) -> float:
        return float(jnp.max(self.array))

    def min_number(self) -> float:
        return float(jnp.min(self.array))

    def std_number(self, bias_corrected: bool = True) -> float:
        return float(jnp.std(self.array, ddof=1 if bias_corrected else 0))

    def var_number(self, bias_corrected: bool = True) -> float:
        return float(jnp.var(self.array, ddof=1 if bias_corrected else 0))

    def norm1_number(self) -> float:
        return float(jnp.sum(jnp.abs(self.array)))

    def norm2_number(self) -> float:
        return float(jnp.sqrt(jnp.sum(self.array * self.array)))

    def norm_max_number(self) -> float:
        return float(jnp.max(jnp.abs(self.array)))

    def amax(self, *dims):
        return self._red(lambda a, axis=None: jnp.max(jnp.abs(a), axis=axis), dims)

    def amin(self, *dims):
        return self._red(lambda a, axis=None: jnp.min(jnp.abs(a), axis=axis), dims)

    def arg_min(self, *dims) -> "INDArray":
        axis = dims[0] if dims else None
        return INDArray(jnp.argmin(self.array, axis=axis))

    def entropy(self) -> float:
        p = self.array
        return float(-jnp.sum(p * jnp.log(jnp.maximum(p, 1e-30))))

    # ---- float-classification / misc (reference isNaN/isInfinite etc.) ----
    def is_nan(self) -> "INDArray":
        return INDArray(jnp.isnan(self.array))

    def is_infinite(self) -> "INDArray":
        return INDArray(jnp.isinf(self.array))

    def replace_where(self, value, condition) -> "INDArray":
        """Reference ``BooleanIndexing.replaceWhere``: set elements matching
        ``condition`` (a :class:`Condition`) to ``value`` (scalar or array)."""
        m = condition(self.array)
        self.array = jnp.where(m, _unwrap(value), self.array)
        return self

    def cond(self, condition) -> "INDArray":
        """Elementwise condition mask (reference ``INDArray.cond``)."""
        return INDArray(condition(self.array).astype(jnp.float32))

    def diag(self) -> "INDArray":
        a = self.array
        return INDArray(jnp.diagflat(a) if a.ndim == 1
                        else jnp.diagonal(a, axis1=-2, axis2=-1))

    def like(self) -> "INDArray":
        return INDArray(jnp.zeros_like(self.array))

    ulike = like

    def pad(self, *paddings) -> "INDArray":
        return INDArray(jnp.pad(self.array, paddings))

    def flatten(self) -> "INDArray":
        return INDArray(self.array.reshape(-1))

    # ---- host access ----
    def numpy(self) -> np.ndarray:
        return np.asarray(self.array)

    def to_int_vector(self):
        return self.numpy().astype(np.int64).reshape(-1).tolist()

    def to_float_vector(self):
        return self.numpy().astype(np.float32).reshape(-1).tolist()

    def to_float_matrix(self):
        return self.numpy().astype(np.float32).tolist()

    def item(self) -> float:
        return self.array.item()

    def to_double_vector(self):
        return self.numpy().astype(np.float64).reshape(-1).tolist()

    # ---- round-3 surface tier (docs/indarray_parity.md tracks coverage) --
    def permutei(self, *axes) -> "INDArray":
        """In-place permute (reference ``permutei``): rebinds the wrapper
        (views-are-copies deviation applies — no aliasing)."""
        self.array = jnp.transpose(self.array, axes)
        return self

    def transposei(self) -> "INDArray":
        self.array = self.array.T
        return self

    def reshapei(self, *shape) -> "INDArray":
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        self.array = self.array.reshape(shape)
        return self

    def raveli(self) -> "INDArray":
        self.array = self.array.reshape(-1)
        return self

    def is_row_vector(self) -> bool:
        return self.array.ndim == 1 or (self.array.ndim == 2
                                        and self.array.shape[0] == 1)

    def is_column_vector(self) -> bool:
        return self.array.ndim == 2 and self.array.shape[1] == 1

    def is_square(self) -> bool:
        return self.array.ndim == 2 \
            and self.array.shape[0] == self.array.shape[1]

    def is_empty(self) -> bool:
        return self.array.size == 0

    def ordering(self) -> str:
        return "c"  # XLA arrays expose row-major logical order

    def stride(self) -> Tuple[int, ...]:
        """Logical C-order strides in ELEMENTS (the reference reports
        buffer strides; XLA's physical tiling is opaque by design)."""
        s, acc = [], 1
        for d in reversed(self.array.shape):
            s.append(acc)
            acc *= int(d)
        return tuple(reversed(s))

    def offset(self) -> int:
        return 0  # no view offsets: views are copies

    def broadcast_to(self, *shape) -> "INDArray":
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = shape[0]
        return self.broadcast(*shape)

    def repmat(self, *reps) -> "INDArray":
        return INDArray(jnp.tile(self.array, reps))

    def tile(self, *reps) -> "INDArray":
        return self.repmat(*reps)

    def sub_array(self, offsets, shape) -> "INDArray":
        sel = tuple(slice(int(o), int(o) + int(s))
                    for o, s in zip(offsets, shape))
        return INDArray(self.array[sel])

    def put_where(self, comp, put):
        """Replace elements where ``comp`` (boolean mask INDArray/array)
        with ``put`` (reference ``putWhere``)."""
        self.array = jnp.where(jnp.asarray(_unwrap(comp), bool),
                               _unwrap(put), self.array)
        return self

    def get_where(self, comp, default=0.0) -> "INDArray":
        """Elements where comp holds, others replaced by ``default``
        (static-shape stand-in for the reference's compacting getWhere)."""
        return INDArray(jnp.where(jnp.asarray(_unwrap(comp), bool),
                                  self.array, default))

    def get_where_with_mask(self, mask, default=0.0) -> "INDArray":
        """Elements where ``mask`` is nonzero, others ``default`` (reference
        ``getWhereWithMask``; static-shape form like ``get_where`` — the
        compacting variant is shape-dynamic and XLA-hostile, so masked-out
        slots carry ``default`` instead of being dropped)."""
        return INDArray(jnp.where(jnp.asarray(_unwrap(mask)) != 0,
                                  self.array, default))

    def eps(self, other, eps: float = 1e-5) -> "INDArray":
        """Elementwise fuzzy equality |a-b| < eps (reference
        ``INDArray.eps`` with ``Nd4j.EPS_THRESHOLD``); returns a 0/1 array
        like the reference's boolean-as-float convention."""
        return INDArray((jnp.abs(self.array - _unwrap(other)) < eps)
                        .astype(self.array.dtype))

    def assign_if(self, value, comp) -> "INDArray":
        return self.put_where(comp, value)

    def fmod(self, other) -> "INDArray":
        return INDArray(jnp.fmod(self.array, _unwrap(other)))

    def fmodi(self, other) -> "INDArray":
        self.array = jnp.fmod(self.array, _unwrap(other))
        return self

    def remainder(self, other) -> "INDArray":
        return INDArray(jnp.remainder(self.array, _unwrap(other)))

    def remainderi(self, other) -> "INDArray":
        self.array = jnp.remainder(self.array, _unwrap(other))
        return self

    def rdivi_row_vector(self, v) -> "INDArray":
        return self._i(self._rowv(v, lambda a, b: b / a))

    def rsubi_row_vector(self, v) -> "INDArray":
        return self._i(self._rowv(v, lambda a, b: b - a))

    def divi_row_vector(self, v) -> "INDArray":
        return self._i(self.div_row_vector(v))

    def subi_row_vector(self, v) -> "INDArray":
        return self._i(self.sub_row_vector(v))

    def addi_column_vector(self, v) -> "INDArray":
        return self._i(self.add_column_vector(v))

    def subi_column_vector(self, v) -> "INDArray":
        return self._i(self.sub_column_vector(v))

    def muli_column_vector(self, v) -> "INDArray":
        return self._i(self.mul_column_vector(v))

    def divi_column_vector(self, v) -> "INDArray":
        return self._i(self.div_column_vector(v))

    def squared_distance(self, other) -> float:
        d = self.array.reshape(-1) - _unwrap(other).reshape(-1)
        return float(jnp.sum(d * d))

    def distance2(self, other) -> float:
        return float(np.sqrt(self.squared_distance(other)))

    def distance1(self, other) -> float:
        d = self.array.reshape(-1) - _unwrap(other).reshape(-1)
        return float(jnp.sum(jnp.abs(d)))

    def median_number(self) -> float:
        return float(jnp.median(self.array))

    def percentile_number(self, q: float) -> float:
        return float(jnp.percentile(self.array, q))

    def cumsumi(self, dim: int = -1) -> "INDArray":
        self.array = jnp.cumsum(self.array, axis=dim)
        return self

    def cumprod(self, dim: int = -1) -> "INDArray":
        return INDArray(jnp.cumprod(self.array, axis=dim))

    def any(self) -> bool:
        return bool(jnp.any(self.array))

    def all(self) -> bool:
        return bool(jnp.all(self.array))

    def none(self) -> bool:
        return not self.any()

    def norm_max(self, *dims):
        if not dims:
            return INDArray(jnp.max(jnp.abs(self.array)))
        return INDArray(jnp.max(jnp.abs(self.array),
                                axis=tuple(int(d) for d in dims)))

    def to_double_matrix(self):
        return self.numpy().astype(np.float64).tolist()

    def to_int_matrix(self):
        return self.numpy().astype(np.int64).tolist()

    def min_index(self) -> int:
        return int(jnp.argmin(self.array))

    def max_index(self) -> int:
        return int(jnp.argmax(self.array))

    def vectors_along_dimension(self, dim: int):
        """Number of 1-D vectors along ``dim`` (reference
        ``vectorsAlongDimension`` count)."""
        return int(self.array.size // self.array.shape[dim])

    def tensors_along_dimension(self, *dims) -> int:
        keep = 1
        for d in dims:
            keep *= self.array.shape[d]
        return int(self.array.size // keep)

    def detach(self) -> "INDArray":
        return self  # no workspaces: arrays are always detached

    def leverage_to(self, _workspace=None) -> "INDArray":
        return self  # workspace no-op (XLA owns memory)

    def __repr__(self):
        return f"INDArray{self.shape()}\n{np.asarray(self.array)}"

    def __len__(self):
        return self.array.shape[0]

    def __jax_array__(self):
        return self.array


class Nd4j:
    """Static factory (reference ``org.nd4j.linalg.factory.Nd4j``)."""

    _rng_key = jax.random.PRNGKey(0)

    @classmethod
    def _next_key(cls):
        cls._rng_key, k = jax.random.split(cls._rng_key)
        return k

    @classmethod
    def set_seed(cls, seed: int) -> None:
        cls._rng_key = jax.random.PRNGKey(int(seed))

    # -- creation --
    @staticmethod
    def create(data=None, *shape) -> INDArray:
        if data is None:
            raise ValueError("Nd4j.create needs data or a shape")
        if isinstance(data, (int,)) or (isinstance(data, (tuple, list))
                                        and shape == ()
                                        and all(isinstance(d, int) for d in data)
                                        and len(data) <= 8
                                        and not any(isinstance(d, (list, tuple, np.ndarray)) for d in data)):
            # create(rows, cols) / create([2, 3]) ambiguity: the reference
            # treats ints as a shape -> zeros
            dims = (data,) + shape if isinstance(data, int) else tuple(data)
            return INDArray(jnp.zeros(dims, jnp.float32))
        arr = jnp.asarray(data, dtype=jnp.float32)
        if shape:
            arr = arr.reshape(shape)
        return INDArray(arr)

    @staticmethod
    def zeros(*shape) -> INDArray:
        return INDArray(jnp.zeros(shape, jnp.float32))

    @staticmethod
    def ones(*shape) -> INDArray:
        return INDArray(jnp.ones(shape, jnp.float32))

    @staticmethod
    def value_array_of(shape, value) -> INDArray:
        return Nd4j.full(shape, value)

    @staticmethod
    def eye(n: int) -> INDArray:
        return INDArray(jnp.eye(n, dtype=jnp.float32))

    @staticmethod
    def scalar(v) -> INDArray:
        return INDArray(jnp.asarray(v, jnp.float32))

    @staticmethod
    def arange(*args) -> INDArray:
        return INDArray(jnp.arange(*args, dtype=jnp.float32))

    @staticmethod
    def linspace(start, stop, num) -> INDArray:
        return INDArray(jnp.linspace(start, stop, int(num), dtype=jnp.float32))

    @classmethod
    def rand(cls, *shape) -> INDArray:
        return INDArray(jax.random.uniform(cls._next_key(), shape, jnp.float32))

    @classmethod
    def randn(cls, *shape) -> INDArray:
        return INDArray(jax.random.normal(cls._next_key(), shape, jnp.float32))

    # -- round-3 factory tier (docs/indarray_parity.md) --
    @staticmethod
    def zeros_like(a) -> INDArray:
        return INDArray(jnp.zeros_like(_unwrap(a)))

    @staticmethod
    def ones_like(a) -> INDArray:
        return INDArray(jnp.ones_like(_unwrap(a)))

    @staticmethod
    def full(shape, value, dtype=jnp.float32) -> INDArray:
        return INDArray(jnp.full(shape, value, dtype))  # int or tuple shape

    @staticmethod
    def empty(dtype=jnp.float32) -> INDArray:
        return INDArray(jnp.zeros((0,), dtype))

    @classmethod
    def rand_int(cls, high, *shape) -> INDArray:
        return INDArray(jax.random.randint(cls._next_key(), shape, 0,
                                           int(high), jnp.int32))

    @classmethod
    def shuffle(cls, a) -> INDArray:
        """Row-shuffled COPY (reference Nd4j.shuffle mutates; functional
        deviation consistent with views-are-copies)."""
        arr = _unwrap(a)
        return INDArray(jax.random.permutation(cls._next_key(), arr, axis=0))

    @classmethod
    def choice(cls, source, n: int) -> INDArray:
        src = _unwrap(source).reshape(-1)
        return INDArray(jax.random.choice(cls._next_key(), src, (int(n),)))

    @staticmethod
    def _pad_edge(a, pad: int, value, axis: int, before: bool) -> INDArray:
        arr = _unwrap(a)
        widths = [(0, 0)] * arr.ndim
        widths[axis] = (int(pad), 0) if before else (0, int(pad))
        return INDArray(jnp.pad(arr, widths, constant_values=value))

    @staticmethod
    def append(a, pad: int, value, axis: int = -1) -> INDArray:
        return Nd4j._pad_edge(a, pad, value, axis, before=False)

    @staticmethod
    def prepend(a, pad: int, value, axis: int = -1) -> INDArray:
        return Nd4j._pad_edge(a, pad, value, axis, before=True)

    @staticmethod
    def rot90(a, k: int = 1) -> INDArray:
        return INDArray(jnp.rot90(_unwrap(a), int(k)))

    @staticmethod
    def flip(a, *axes) -> INDArray:
        return INDArray(jnp.flip(_unwrap(a), axes or None))

    @staticmethod
    def diag(a, k: int = 0) -> INDArray:
        """Vector -> diagonal matrix, matrix/batch -> diagonal vector(s).
        Delegates to INDArray.diag for k=0 (one source of truth)."""
        if k == 0:
            return INDArray(_unwrap(a)).diag()
        return INDArray(jnp.diag(_unwrap(a), int(k)))

    @staticmethod
    def repeat(a, repeats: int, axis: Optional[int] = None) -> INDArray:
        arr = INDArray(_unwrap(a))
        return arr.repeat(axis, int(repeats)) if axis is not None \
            else INDArray(jnp.repeat(arr.array, int(repeats)))

    @staticmethod
    def tile(a, *reps) -> INDArray:
        return INDArray(_unwrap(a)).tile(*reps)

    @staticmethod
    def cumsum(a, axis: int = -1) -> INDArray:
        return INDArray(_unwrap(a)).cumsum(axis)

    # -- combination --
    @staticmethod
    def vstack(*arrs) -> INDArray:
        return INDArray(jnp.vstack([_unwrap(a) for a in arrs]))

    @staticmethod
    def hstack(*arrs) -> INDArray:
        return INDArray(jnp.hstack([_unwrap(a) for a in arrs]))

    @staticmethod
    def concat(dim: int, *arrs) -> INDArray:
        return INDArray(jnp.concatenate([_unwrap(a) for a in arrs], axis=dim))

    @staticmethod
    def stack(dim: int, *arrs) -> INDArray:
        return INDArray(jnp.stack([_unwrap(a) for a in arrs], axis=dim))

    @staticmethod
    def to_flattened(*arrs) -> INDArray:
        return INDArray(jnp.concatenate([_unwrap(a).reshape(-1) for a in arrs]))

    # -- linalg --
    @staticmethod
    def gemm(a, b, transpose_a: bool = False, transpose_b: bool = False,
             alpha: float = 1.0, beta: float = 0.0, c=None) -> INDArray:
        A, B = _unwrap(a), _unwrap(b)
        if transpose_a:
            A = A.T
        if transpose_b:
            B = B.T
        out = alpha * (A @ B)
        if c is not None and beta != 0.0:
            out = out + beta * _unwrap(c)
        return INDArray(out)

    @staticmethod
    def dot(a, b) -> INDArray:
        return INDArray(jnp.dot(_unwrap(a), _unwrap(b)))

    # -- sorting --
    @staticmethod
    def sort(a, dim: int = -1, ascending: bool = True) -> INDArray:
        out = jnp.sort(_unwrap(a), axis=dim)
        return INDArray(out if ascending else jnp.flip(out, axis=dim))

    @staticmethod
    def arg_sort(a, dim: int = -1) -> INDArray:
        return INDArray(jnp.argsort(_unwrap(a), axis=dim))

    # -- io (reference Nd4j.write/read binary) --
    @staticmethod
    def write(arr, path: str) -> None:
        np.save(path if path.endswith(".npy") else path + ".npy",
                np.asarray(_unwrap(arr)))

    @staticmethod
    def read(path: str) -> INDArray:
        return INDArray(np.load(path if path.endswith(".npy") else path + ".npy"))

    # -- expand --
    @staticmethod
    def expand_dims(a, dim: int) -> INDArray:
        return INDArray(jnp.expand_dims(_unwrap(a), dim))

    @staticmethod
    def squeeze(a, dim: int) -> INDArray:
        return INDArray(jnp.squeeze(_unwrap(a), axis=dim))

    @staticmethod
    def where(cond, x, y) -> INDArray:
        return INDArray(jnp.where(_unwrap(cond), _unwrap(x), _unwrap(y)))

    @staticmethod
    def exec(op_name: str, *arrs, **kwargs) -> INDArray:
        """Named-op dispatch into the op registry (the ``Nd4j.exec`` analog;
        ops come from ``autodiff.ops_registry`` — same names SameDiff uses)."""
        from deeplearning4j_tpu.autodiff.ops_registry import get_op
        return INDArray(get_op(op_name)(*[_unwrap(a) for a in arrs], **kwargs))


class Conditions:
    """Reference ``org.nd4j.linalg.indexing.conditions.Conditions``: factory
    of elementwise predicates for ``BooleanIndexing`` / ``replace_where``."""

    @staticmethod
    def less_than(v):
        return lambda a: a < v

    @staticmethod
    def less_than_or_equal(v):
        return lambda a: a <= v

    @staticmethod
    def greater_than(v):
        return lambda a: a > v

    @staticmethod
    def greater_than_or_equal(v):
        return lambda a: a >= v

    @staticmethod
    def equals(v):
        return lambda a: a == v

    @staticmethod
    def not_equals(v):
        return lambda a: a != v

    @staticmethod
    def abs_greater_than(v):
        return lambda a: jnp.abs(a) > v

    @staticmethod
    def abs_less_than(v):
        return lambda a: jnp.abs(a) < v

    @staticmethod
    def is_nan():
        return jnp.isnan

    @staticmethod
    def is_infinite():
        return jnp.isinf


class BooleanIndexing:
    """Reference ``org.nd4j.linalg.indexing.BooleanIndexing``."""

    @staticmethod
    def replace_where(arr, value, condition):
        return _as_ind(arr).replace_where(value, condition)

    @staticmethod
    def and_(arr, condition) -> bool:
        return bool(jnp.all(condition(_unwrap(arr))))

    @staticmethod
    def or_(arr, condition) -> bool:
        return bool(jnp.any(condition(_unwrap(arr))))


def _as_ind(x) -> INDArray:
    return x if isinstance(x, INDArray) else INDArray(jnp.asarray(x))


class Transforms:
    """Reference ``org.nd4j.linalg.ops.transforms.Transforms``: the
    free-function math API over INDArrays. Thin jnp delegation — everything
    jit-composes."""

    @staticmethod
    def _u(fn, x) -> INDArray:
        return INDArray(fn(_unwrap(x)))

    exp = staticmethod(lambda x: Transforms._u(jnp.exp, x))
    log = staticmethod(lambda x: Transforms._u(jnp.log, x))
    sqrt = staticmethod(lambda x: Transforms._u(jnp.sqrt, x))
    abs = staticmethod(lambda x: Transforms._u(jnp.abs, x))
    sign = staticmethod(lambda x: Transforms._u(jnp.sign, x))
    floor = staticmethod(lambda x: Transforms._u(jnp.floor, x))
    ceil = staticmethod(lambda x: Transforms._u(jnp.ceil, x))
    round = staticmethod(lambda x: Transforms._u(jnp.round, x))
    sin = staticmethod(lambda x: Transforms._u(jnp.sin, x))
    cos = staticmethod(lambda x: Transforms._u(jnp.cos, x))
    tanh = staticmethod(lambda x: Transforms._u(jnp.tanh, x))
    sigmoid = staticmethod(lambda x: Transforms._u(jax.nn.sigmoid, x))
    softmax = staticmethod(lambda x: Transforms._u(
        lambda a: jax.nn.softmax(a, axis=-1), x))
    relu = staticmethod(lambda x: Transforms._u(jax.nn.relu, x))
    leaky_relu = staticmethod(lambda x, alpha=0.01: INDArray(
        jax.nn.leaky_relu(_unwrap(x), alpha)))
    elu = staticmethod(lambda x: Transforms._u(jax.nn.elu, x))
    soft_plus = staticmethod(lambda x: Transforms._u(jax.nn.softplus, x))
    hard_tanh = staticmethod(lambda x: Transforms._u(
        lambda a: jnp.clip(a, -1.0, 1.0), x))

    @staticmethod
    def pow(x, p) -> INDArray:
        return INDArray(jnp.power(_unwrap(x), _unwrap(p) if isinstance(p, INDArray) else p))

    @staticmethod
    def max(x, v) -> INDArray:
        return INDArray(jnp.maximum(_unwrap(x), _unwrap(v) if isinstance(v, INDArray) else v))

    @staticmethod
    def min(x, v) -> INDArray:
        return INDArray(jnp.minimum(_unwrap(x), _unwrap(v) if isinstance(v, INDArray) else v))

    @staticmethod
    def unit_vec(x) -> INDArray:
        a = _unwrap(x)
        return INDArray(a / jnp.maximum(jnp.sqrt(jnp.sum(a * a)), 1e-12))

    @staticmethod
    def normalize_zero_mean_and_unit_variance(x) -> INDArray:
        a = _unwrap(x)
        return INDArray((a - jnp.mean(a)) / jnp.maximum(jnp.std(a), 1e-12))

    @staticmethod
    def cosine_sim(a, b) -> float:
        a, b = _unwrap(a).ravel(), _unwrap(b).ravel()
        denom = jnp.sqrt(jnp.sum(a * a)) * jnp.sqrt(jnp.sum(b * b))
        return float(jnp.sum(a * b) / jnp.maximum(denom, 1e-12))

    @staticmethod
    def cosine_distance(a, b) -> float:
        return 1.0 - Transforms.cosine_sim(a, b)

    @staticmethod
    def euclidean_distance(a, b) -> float:
        d = _unwrap(a).ravel() - _unwrap(b).ravel()
        return float(jnp.sqrt(jnp.sum(d * d)))

    @staticmethod
    def manhattan_distance(a, b) -> float:
        return float(jnp.sum(jnp.abs(_unwrap(a).ravel() - _unwrap(b).ravel())))

    @staticmethod
    def hamming_distance(a, b) -> float:
        return float(jnp.mean(
            (_unwrap(a).ravel() != _unwrap(b).ravel()).astype(jnp.float32)))

    @staticmethod
    def all_cosine_similarities(matrix, vector) -> INDArray:
        """Row-wise cosine similarity of ``matrix`` rows against ``vector``
        (the word2vec nearest-neighbour primitive) — one fused program."""
        m, v = _unwrap(matrix), _unwrap(vector).ravel()
        num = m @ v
        den = jnp.sqrt(jnp.sum(m * m, axis=1)) * jnp.sqrt(jnp.sum(v * v))
        return INDArray(num / jnp.maximum(den, 1e-12))

    @staticmethod
    def dot(a, b) -> float:
        return float(jnp.sum(_unwrap(a).ravel() * _unwrap(b).ravel()))
