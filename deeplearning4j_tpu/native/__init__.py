"""Native host runtime components (C++, ctypes-bound).

Where the reference's host runtime is native (libnd4j compression kernels,
OpenCV image loader — SURVEY.md §2.1), this package builds the equivalents
as a C++ shared library at first use (g++ -O3, cached next to the sources)
and binds via ctypes:

- ``ThresholdCodec`` — sparse sign-indexed + bitmap gradient compression
  with residual accumulation (the reference's distributed wire format;
  relevant on the DCN path, a documented non-goal over ICI).
- ``ImagePipeline`` — multithreaded uint8→float conversion, per-channel
  normalization, batched random crop/flip augmentation (everything after
  JPEG entropy decode, which TF's native op already covers).

Pure-numpy fallbacks keep the package usable if no compiler is present.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libdl4jtpu_host.so")
_SOURCES = ["threshold_codec.cpp", "image_pipeline.cpp"]

_lock = threading.Lock()  # guards: (_lib/_build_failed lazy dlopen)
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _compile(srcs, out_path, extra_flags=(), headers=(), timeout=180,
             march_native=True) -> Optional[str]:
    """Shared compile-and-cache: rebuild ``out_path`` when any source or
    header is newer; atomic output (compile to .tmp, rename) so concurrent
    builders never dlopen a half-written .so."""
    newest = max(os.path.getmtime(f) for f in tuple(srcs) + tuple(headers))
    if os.path.exists(out_path) and os.path.getmtime(out_path) >= newest:
        return out_path
    # unique per BUILDER, not just per process: since the compile runs
    # outside the module lock (lockdep: no subprocess wait under a lock),
    # two cold-start threads may race _compile on the same output — each
    # needs its own tmp so neither can truncate or unlink the other's
    # in-progress object; the atomic rename publishes whichever finishes
    tmp = out_path + f".tmp.{os.getpid()}.{threading.get_ident()}"
    base = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread"]
    variants = ([base + ["-march=native"], base] if march_native else [base])
    for cc in variants:
        try:
            subprocess.run(cc + ["-o", tmp] + list(srcs) + list(extra_flags),
                           check=True, capture_output=True, timeout=timeout)
            os.replace(tmp, out_path)
            return out_path
        except (subprocess.SubprocessError, FileNotFoundError, OSError):
            continue
        finally:
            if os.path.exists(tmp):
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
    return None


def _build() -> Optional[str]:
    return _compile([os.path.join(_DIR, s) for s in _SOURCES], _LIB_PATH,
                    timeout=120)


def get_lib() -> Optional[ctypes.CDLL]:
    """Compile-on-first-use loader; None if no toolchain (fallback mode).

    The compile itself runs OUTSIDE ``_lock`` (lockdep: never hold a lock
    across a subprocess wait — ISSUE 14). ``_compile`` is idempotent and
    atomic (mtime skip, per-PID tmp + rename), so two cold-start racers
    at worst both compile and the loser's rename is a no-op overwrite of
    identical bytes; publication under the lock stays single-assignment."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
    path = _build()
    with _lock:
        if _lib is not None or _build_failed:   # raced: first racer won
            return _lib
        if path is None:
            _build_failed = True
            return None
        lib = ctypes.CDLL(path)
        c_f32p = ctypes.POINTER(ctypes.c_float)
        c_i32p = ctypes.POINTER(ctypes.c_int32)
        c_u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.threshold_encode.restype = ctypes.c_int64
        lib.threshold_encode.argtypes = [c_f32p, c_f32p, ctypes.c_int64,
                                         ctypes.c_float, c_i32p, ctypes.c_int64]
        lib.threshold_decode.restype = None
        lib.threshold_decode.argtypes = [c_i32p, ctypes.c_int64, ctypes.c_float,
                                         c_f32p, ctypes.c_int64]
        lib.threshold_count.restype = ctypes.c_int64
        lib.threshold_count.argtypes = [c_f32p, c_f32p, ctypes.c_int64,
                                        ctypes.c_float]
        lib.bitmap_encode.restype = ctypes.c_int64
        lib.bitmap_encode.argtypes = [c_f32p, c_f32p, ctypes.c_int64,
                                      ctypes.c_float, c_u8p]
        lib.bitmap_decode.restype = None
        lib.bitmap_decode.argtypes = [c_u8p, ctypes.c_int64, ctypes.c_float, c_f32p]
        lib.u8_to_f32.restype = None
        lib.u8_to_f32.argtypes = [c_u8p, c_f32p, ctypes.c_int64, ctypes.c_float,
                                  ctypes.c_float, ctypes.c_int32]
        lib.normalize_nhwc.restype = None
        lib.normalize_nhwc.argtypes = [c_u8p, c_f32p, ctypes.c_int64,
                                       ctypes.c_int32, c_f32p, c_f32p]
        lib.random_crop_flip_batch.restype = None
        lib.random_crop_flip_batch.argtypes = [
            c_u8p, c_u8p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_uint64,
            ctypes.c_int32, ctypes.c_int32]
        _lib = lib
        return _lib


def _fp(a: np.ndarray, typ):
    return a.ctypes.data_as(typ)


class ThresholdCodec:
    """Sparse threshold gradient codec with residual state (reference
    ``EncodedGradientsAccumulator`` wire format).

    Input hardening (ISSUE 6 codec satellite — these were silent
    out-of-bounds reads or wrong-answer paths before):

    - ``encode``/``encode_bitmap`` require ``grad.size == self.size``; a
      shorter buffer used to make the C kernel read past its end, a longer
      one silently dropped the tail.
    - ``decode``/``decode_bitmap`` validate a caller-supplied ``target``
      (f32, contiguous, exactly ``size`` elements) — the ctypes cast would
      otherwise reinterpret f64 memory as f32 and scribble garbage.
    - ``decode_bitmap`` rejects truncated buffers (the C loop indexes
      ``encoded[n >> 2]`` unconditionally).
    - the numpy ``decode`` fallback now matches the C kernel's semantics
      on invalid indices: 0 and out-of-range entries are *ignored* (0 used
      to wrap to ``target[-1]``).
    - bitmap encode/decode have bit-exact numpy fallbacks, so a
      toolchain-less host degrades instead of raising.
    """

    def __init__(self, size: int, threshold: float = 1e-3):
        self.size = int(size)
        self.threshold = float(threshold)
        self.residual = np.zeros(self.size, np.float32)

    def _check_grad(self, grad: np.ndarray) -> np.ndarray:
        grad = np.ascontiguousarray(grad, np.float32).reshape(-1)
        if grad.size != self.size:
            raise ValueError(
                f"grad has {grad.size} elements, codec expects {self.size}")
        return grad

    def _check_target(self, target: Optional[np.ndarray]) -> np.ndarray:
        if target is None:
            return np.zeros(self.size, np.float32)
        if (target.dtype != np.float32 or target.ndim != 1
                or target.size != self.size
                or not target.flags.c_contiguous):
            # 1-D is part of the contract: the numpy fallbacks index the
            # target directly (a (10,10) view would row-index)
            raise ValueError(
                f"target must be a contiguous 1-D float32 array of "
                f"{self.size} elements, got {target.dtype}{target.shape}")
        return target

    def encode(self, grad: np.ndarray) -> np.ndarray:
        grad = self._check_grad(grad)
        lib = get_lib()
        if lib is not None and self.size:
            out = np.empty(self.size, np.int32)
            n = lib.threshold_encode(
                _fp(grad, ctypes.POINTER(ctypes.c_float)),
                _fp(self.residual, ctypes.POINTER(ctypes.c_float)),
                self.size, self.threshold,
                _fp(out, ctypes.POINTER(ctypes.c_int32)), self.size)
            return out[:n].copy()
        # numpy fallback (kept bit-identical to the C kernel)
        acc = grad + self.residual
        pos = acc >= self.threshold
        neg = acc <= -self.threshold
        idx = np.nonzero(pos | neg)[0]
        # sign convention matches the C kernel: `acc >= threshold` emits a
        # positive index (threshold 0 ties encode as +0 contributions)
        encoded = np.where(acc[idx] >= self.threshold,
                           idx + 1, -(idx + 1)).astype(np.int32)
        self.residual = acc
        self.residual[idx] -= np.where(encoded > 0, self.threshold,
                                       -self.threshold).astype(np.float32)
        return encoded

    def decode(self, encoded: np.ndarray, target: Optional[np.ndarray] = None
               ) -> np.ndarray:
        target = self._check_target(target)
        encoded = np.ascontiguousarray(encoded, np.int32).reshape(-1)
        lib = get_lib()
        if len(encoded) == 0:
            return target
        if lib is not None:
            lib.threshold_decode(
                _fp(encoded, ctypes.POINTER(ctypes.c_int32)), len(encoded),
                self.threshold, _fp(target, ctypes.POINTER(ctypes.c_float)),
                self.size)
            return target
        # match C semantics: invalid indices (0, |idx| > size) are ignored
        valid = encoded[(np.abs(encoded) >= 1) & (np.abs(encoded) <= self.size)]
        idx = np.abs(valid) - 1
        np.add.at(target, idx,
                  np.where(valid > 0, self.threshold,
                           -self.threshold).astype(np.float32))
        return target

    def bitmap_nbytes(self) -> int:
        """Wire size of a bitmap encoding: 2 bits per element."""
        return (self.size + 3) // 4

    def encode_bitmap(self, grad: np.ndarray) -> np.ndarray:
        grad = self._check_grad(grad)
        lib = get_lib()
        nbytes = self.bitmap_nbytes()
        if lib is not None and self.size:
            out = np.empty(nbytes, np.uint8)
            lib.bitmap_encode(
                _fp(grad, ctypes.POINTER(ctypes.c_float)),
                _fp(self.residual, ctypes.POINTER(ctypes.c_float)),
                self.size, self.threshold, _fp(out, ctypes.POINTER(ctypes.c_uint8)))
            return out
        # numpy fallback: same 2-bit little-endian packing as the C kernel
        acc = grad + self.residual
        code = np.zeros(self.size, np.uint8)
        code[acc >= self.threshold] = 1
        code[acc <= -self.threshold] = 2
        self.residual = acc - np.where(
            code == 1, self.threshold,
            np.where(code == 2, -self.threshold, 0.0)).astype(np.float32)
        padded = np.zeros(nbytes * 4, np.uint8)
        padded[:self.size] = code
        quads = padded.reshape(-1, 4)
        return (quads[:, 0] | (quads[:, 1] << 2) | (quads[:, 2] << 4)
                | (quads[:, 3] << 6)).astype(np.uint8)

    def decode_bitmap(self, encoded: np.ndarray,
                      target: Optional[np.ndarray] = None) -> np.ndarray:
        target = self._check_target(target)
        encoded = np.ascontiguousarray(encoded, np.uint8).reshape(-1)
        nbytes = self.bitmap_nbytes()
        if len(encoded) < nbytes:
            raise ValueError(f"bitmap buffer has {len(encoded)} bytes, "
                             f"need {nbytes} for {self.size} elements")
        if self.size == 0:
            return target
        lib = get_lib()
        if lib is not None:
            lib.bitmap_decode(_fp(encoded, ctypes.POINTER(ctypes.c_uint8)),
                              self.size, self.threshold,
                              _fp(target, ctypes.POINTER(ctypes.c_float)))
            return target
        quads = encoded[:nbytes]
        code = np.empty(nbytes * 4, np.uint8)
        code[0::4] = quads & 3
        code[1::4] = (quads >> 2) & 3
        code[2::4] = (quads >> 4) & 3
        code[3::4] = (quads >> 6) & 3
        code = code[:self.size]
        target[code == 1] += self.threshold
        target[code == 2] -= self.threshold
        return target


class TreeCodec:
    """Threshold codec over a *flat param tree* — the ergonomics layer the
    distributed trainer feeds (reference: ``EncodedGradientsAccumulator``
    operates on the flattened-update view the updater blocks share).

    Built from a list of template leaves (e.g. ``jax.tree.leaves(grads)``
    materialized as numpy); owns the offsets, one residual buffer across
    the whole tree, and the sparse/bitmap format choice:

    - ``flatten(leaves)`` → one contiguous f32 vector
    - ``unflatten(flat)`` → list of per-leaf arrays (template shapes)
    - ``encode(flat)`` → ``(format, payload_bytes)`` where format is
      ``FORMAT_SPARSE`` or ``FORMAT_BITMAP`` — chosen per call by
      *predicted* wire size (the residual makes encoding stateful, so the
      choice must happen before either encoder mutates it)
    - ``decode_into(format, payload, target)`` — accumulate a peer's
      encoded contribution into ``target``
    """

    FORMAT_DENSE = 0
    FORMAT_SPARSE = 1
    FORMAT_BITMAP = 2

    def __init__(self, leaves, threshold: float = 1e-3):
        self.shapes = [tuple(np.shape(l)) for l in leaves]
        self.sizes = [int(np.prod(s)) if s else 1 for s in self.shapes]
        self.offsets = np.cumsum([0] + self.sizes)
        self.size = int(self.offsets[-1])
        self.threshold = float(threshold)
        self.codec = ThresholdCodec(self.size, threshold=self.threshold)

    @property
    def residual(self) -> np.ndarray:
        return self.codec.residual

    @residual.setter
    def residual(self, value: np.ndarray) -> None:
        value = np.ascontiguousarray(value, np.float32).reshape(-1)
        if value.size != self.size:
            raise ValueError(f"residual has {value.size} elements, "
                             f"codec expects {self.size}")
        self.codec.residual = value

    def flatten(self, leaves) -> np.ndarray:
        if len(leaves) != len(self.sizes):
            raise ValueError(f"tree has {len(leaves)} leaves, codec "
                             f"expects {len(self.sizes)}")
        out = np.empty(self.size, np.float32)
        for i, (leaf, lo, sz) in enumerate(
                zip(leaves, self.offsets, self.sizes)):
            flat = np.asarray(leaf, np.float32).reshape(-1)
            if flat.size != sz:
                # a size-1 leaf would silently broadcast into the slot
                raise ValueError(f"leaf {i} has {flat.size} elements, "
                                 f"template slot holds {sz}")
            out[lo:lo + sz] = flat
        return out

    def unflatten(self, flat: np.ndarray):
        flat = np.ascontiguousarray(flat, np.float32).reshape(-1)
        if flat.size != self.size:
            raise ValueError(f"flat vector has {flat.size} elements, "
                             f"codec expects {self.size}")
        return [flat[lo:lo + sz].reshape(shape) for lo, sz, shape in
                zip(self.offsets, self.sizes, self.shapes)]

    def predicted_format(self, flat: np.ndarray) -> int:
        """Sparse-vs-bitmap choice by predicted wire size, *without*
        touching the residual: count of would-be-emitted elements * 4
        bytes against the fixed 2-bit bitmap. The count is a fused single
        C pass (no temporaries) when the native lib is present."""
        lib = get_lib()
        if lib is not None and self.size:
            flat32 = self.codec._check_grad(flat)
            n_hits = int(lib.threshold_count(
                _fp(flat32, ctypes.POINTER(ctypes.c_float)),
                _fp(self.codec.residual, ctypes.POINTER(ctypes.c_float)),
                self.size, self.threshold))
        else:
            n_hits = int(np.count_nonzero(
                np.abs(flat + self.codec.residual) >= self.threshold))
        return (self.FORMAT_SPARSE if n_hits * 4 <= self.codec.bitmap_nbytes()
                else self.FORMAT_BITMAP)

    def encode(self, flat: np.ndarray):
        fmt = self.predicted_format(flat)
        if fmt == self.FORMAT_SPARSE:
            return fmt, self.codec.encode(flat).tobytes()
        return fmt, self.codec.encode_bitmap(flat).tobytes()

    def decode_into(self, fmt: int, payload: bytes,
                    target: np.ndarray) -> np.ndarray:
        if fmt == self.FORMAT_SPARSE:
            return self.codec.decode(np.frombuffer(payload, np.int32), target)
        if fmt == self.FORMAT_BITMAP:
            return self.codec.decode_bitmap(
                np.frombuffer(payload, np.uint8), target)
        raise ValueError(f"unknown encoded-update format {fmt}")


class ImagePipeline:
    """Multithreaded post-decode image batch ops."""

    def __init__(self, n_threads: Optional[int] = None):
        self.n_threads = n_threads or min(8, os.cpu_count() or 1)

    def to_float(self, batch_u8: np.ndarray, scale: float = 1.0 / 255.0,
                 shift: float = 0.0) -> np.ndarray:
        batch_u8 = np.ascontiguousarray(batch_u8, np.uint8)
        out = np.empty(batch_u8.shape, np.float32)
        lib = get_lib()
        if lib is not None:
            lib.u8_to_f32(_fp(batch_u8, ctypes.POINTER(ctypes.c_uint8)),
                          _fp(out, ctypes.POINTER(ctypes.c_float)),
                          batch_u8.size, scale, shift, self.n_threads)
            return out
        return batch_u8.astype(np.float32) * scale + shift

    def normalize(self, batch_u8: np.ndarray, mean, std) -> np.ndarray:
        """(..., C) uint8 -> float32 (x/255 - mean)/std per channel."""
        batch_u8 = np.ascontiguousarray(batch_u8, np.uint8)
        c = batch_u8.shape[-1]
        mean = np.ascontiguousarray(mean, np.float32)
        std = np.ascontiguousarray(std, np.float32)
        out = np.empty(batch_u8.shape, np.float32)
        lib = get_lib()
        if lib is not None:
            lib.normalize_nhwc(_fp(batch_u8, ctypes.POINTER(ctypes.c_uint8)),
                               _fp(out, ctypes.POINTER(ctypes.c_float)),
                               batch_u8.size // c, c,
                               _fp(mean, ctypes.POINTER(ctypes.c_float)),
                               _fp(std, ctypes.POINTER(ctypes.c_float)))
            return out
        return (batch_u8.astype(np.float32) / 255.0 - mean) / std

    def random_crop_flip(self, batch_u8: np.ndarray, out_h: int, out_w: int,
                         seed: int = 0, flip: bool = True) -> np.ndarray:
        """(B, H, W, C) uint8 -> (B, out_h, out_w, C) uint8, deterministic
        per (seed, image-index)."""
        batch_u8 = np.ascontiguousarray(batch_u8, np.uint8)
        b, h, w, c = batch_u8.shape
        out = np.empty((b, out_h, out_w, c), np.uint8)
        lib = get_lib()
        if lib is not None:
            lib.random_crop_flip_batch(
                _fp(batch_u8, ctypes.POINTER(ctypes.c_uint8)),
                _fp(out, ctypes.POINTER(ctypes.c_uint8)),
                b, h, w, out_h, out_w, c, seed, int(flip), self.n_threads)
            return out
        rng = np.random.default_rng(seed)
        for i in range(b):
            oy = rng.integers(0, h - out_h + 1) if h > out_h else 0
            ox = rng.integers(0, w - out_w + 1) if w > out_w else 0
            img = batch_u8[i, oy:oy + out_h, ox:ox + out_w]
            if flip and rng.integers(0, 2):
                img = img[:, ::-1]
            out[i] = img
        return out


# --------------------------------------------------------------- C API build
_CAPI_LIB = os.path.join(_DIR, "libdl4jtpu_capi.so")


def build_capi(force: bool = False) -> Optional[str]:
    """Build the embedding C API (capi.cpp + dl4j_tpu_c.h): the language-
    bindings surface for C/C++ host applications (reference [U] jumpy/
    pydl4j/ nd4s — direction inverted, see dl4j_tpu_c.h). Returns the .so
    path, or None when no toolchain/libpython is available."""
    import sysconfig
    src = os.path.join(_DIR, "capi.cpp")
    hdr = os.path.join(_DIR, "dl4j_tpu_c.h")
    # the unlink is the only shared-state mutation; the compile itself
    # runs OUTSIDE _lock (lockdep: never hold a lock across a subprocess
    # wait — _compile is idempotent and atomic, same contract as get_lib)
    with _lock:
        if force and os.path.exists(_CAPI_LIB):
            os.unlink(_CAPI_LIB)
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ver = sysconfig.get_config_var("LDVERSION") or "3"
    return _compile(
        [src], _CAPI_LIB, headers=[hdr], march_native=False,
        extra_flags=[f"-I{inc}", f"-L{libdir}", f"-Wl,-rpath,{libdir}",
                     f"-lpython{ver}"])
