"""Native host runtime components (C++, ctypes-bound).

Where the reference's host runtime is native (libnd4j compression kernels,
OpenCV image loader — SURVEY.md §2.1), this package builds the equivalents
as a C++ shared library at first use (g++ -O3, cached next to the sources)
and binds via ctypes:

- ``ThresholdCodec`` — sparse sign-indexed + bitmap gradient compression
  with residual accumulation (the reference's distributed wire format;
  relevant on the DCN path, a documented non-goal over ICI).
- ``ImagePipeline`` — multithreaded uint8→float conversion, per-channel
  normalization, batched random crop/flip augmentation (everything after
  JPEG entropy decode, which TF's native op already covers).

Pure-numpy fallbacks keep the package usable if no compiler is present.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libdl4jtpu_host.so")
_SOURCES = ["threshold_codec.cpp", "image_pipeline.cpp"]

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _compile(srcs, out_path, extra_flags=(), headers=(), timeout=180,
             march_native=True) -> Optional[str]:
    """Shared compile-and-cache: rebuild ``out_path`` when any source or
    header is newer; atomic output (compile to .tmp, rename) so concurrent
    builders never dlopen a half-written .so."""
    newest = max(os.path.getmtime(f) for f in tuple(srcs) + tuple(headers))
    if os.path.exists(out_path) and os.path.getmtime(out_path) >= newest:
        return out_path
    tmp = out_path + f".tmp.{os.getpid()}"
    base = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread"]
    variants = ([base + ["-march=native"], base] if march_native else [base])
    for cc in variants:
        try:
            subprocess.run(cc + ["-o", tmp] + list(srcs) + list(extra_flags),
                           check=True, capture_output=True, timeout=timeout)
            os.replace(tmp, out_path)
            return out_path
        except (subprocess.SubprocessError, FileNotFoundError, OSError):
            continue
        finally:
            if os.path.exists(tmp):
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
    return None


def _build() -> Optional[str]:
    return _compile([os.path.join(_DIR, s) for s in _SOURCES], _LIB_PATH,
                    timeout=120)


def get_lib() -> Optional[ctypes.CDLL]:
    """Compile-on-first-use loader; None if no toolchain (fallback mode)."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        path = _build()
        if path is None:
            _build_failed = True
            return None
        lib = ctypes.CDLL(path)
        c_f32p = ctypes.POINTER(ctypes.c_float)
        c_i32p = ctypes.POINTER(ctypes.c_int32)
        c_u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.threshold_encode.restype = ctypes.c_int64
        lib.threshold_encode.argtypes = [c_f32p, c_f32p, ctypes.c_int64,
                                         ctypes.c_float, c_i32p, ctypes.c_int64]
        lib.threshold_decode.restype = None
        lib.threshold_decode.argtypes = [c_i32p, ctypes.c_int64, ctypes.c_float,
                                         c_f32p, ctypes.c_int64]
        lib.bitmap_encode.restype = ctypes.c_int64
        lib.bitmap_encode.argtypes = [c_f32p, c_f32p, ctypes.c_int64,
                                      ctypes.c_float, c_u8p]
        lib.bitmap_decode.restype = None
        lib.bitmap_decode.argtypes = [c_u8p, ctypes.c_int64, ctypes.c_float, c_f32p]
        lib.u8_to_f32.restype = None
        lib.u8_to_f32.argtypes = [c_u8p, c_f32p, ctypes.c_int64, ctypes.c_float,
                                  ctypes.c_float, ctypes.c_int32]
        lib.normalize_nhwc.restype = None
        lib.normalize_nhwc.argtypes = [c_u8p, c_f32p, ctypes.c_int64,
                                       ctypes.c_int32, c_f32p, c_f32p]
        lib.random_crop_flip_batch.restype = None
        lib.random_crop_flip_batch.argtypes = [
            c_u8p, c_u8p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_uint64,
            ctypes.c_int32, ctypes.c_int32]
        _lib = lib
        return _lib


def _fp(a: np.ndarray, typ):
    return a.ctypes.data_as(typ)


class ThresholdCodec:
    """Sparse threshold gradient codec with residual state (reference
    ``EncodedGradientsAccumulator`` wire format)."""

    def __init__(self, size: int, threshold: float = 1e-3):
        self.size = int(size)
        self.threshold = float(threshold)
        self.residual = np.zeros(self.size, np.float32)

    def encode(self, grad: np.ndarray) -> np.ndarray:
        grad = np.ascontiguousarray(grad.reshape(-1), np.float32)
        lib = get_lib()
        if lib is not None:
            out = np.empty(self.size, np.int32)
            n = lib.threshold_encode(
                _fp(grad, ctypes.POINTER(ctypes.c_float)),
                _fp(self.residual, ctypes.POINTER(ctypes.c_float)),
                self.size, self.threshold,
                _fp(out, ctypes.POINTER(ctypes.c_int32)), self.size)
            return out[:n].copy()
        # numpy fallback
        acc = grad + self.residual
        pos = acc >= self.threshold
        neg = acc <= -self.threshold
        idx = np.nonzero(pos | neg)[0]
        encoded = np.where(acc[idx] > 0, idx + 1, -(idx + 1)).astype(np.int32)
        self.residual = acc
        self.residual[idx] -= np.sign(acc[idx]) * self.threshold
        return encoded

    def decode(self, encoded: np.ndarray, target: Optional[np.ndarray] = None
               ) -> np.ndarray:
        if target is None:
            target = np.zeros(self.size, np.float32)
        encoded = np.ascontiguousarray(encoded, np.int32)
        lib = get_lib()
        if lib is not None:
            lib.threshold_decode(
                _fp(encoded, ctypes.POINTER(ctypes.c_int32)), len(encoded),
                self.threshold, _fp(target, ctypes.POINTER(ctypes.c_float)),
                self.size)
            return target
        idx = np.abs(encoded) - 1
        target[idx] += np.sign(encoded) * self.threshold
        return target

    def encode_bitmap(self, grad: np.ndarray) -> np.ndarray:
        grad = np.ascontiguousarray(grad.reshape(-1), np.float32)
        lib = get_lib()
        nbytes = (self.size + 3) // 4
        if lib is not None:
            out = np.empty(nbytes, np.uint8)
            lib.bitmap_encode(
                _fp(grad, ctypes.POINTER(ctypes.c_float)),
                _fp(self.residual, ctypes.POINTER(ctypes.c_float)),
                self.size, self.threshold, _fp(out, ctypes.POINTER(ctypes.c_uint8)))
            return out
        raise RuntimeError("bitmap encoding requires the native library")

    def decode_bitmap(self, encoded: np.ndarray,
                      target: Optional[np.ndarray] = None) -> np.ndarray:
        if target is None:
            target = np.zeros(self.size, np.float32)
        lib = get_lib()
        if lib is None:
            raise RuntimeError("bitmap decoding requires the native library")
        lib.bitmap_decode(_fp(np.ascontiguousarray(encoded, np.uint8),
                              ctypes.POINTER(ctypes.c_uint8)),
                          self.size, self.threshold,
                          _fp(target, ctypes.POINTER(ctypes.c_float)))
        return target


class ImagePipeline:
    """Multithreaded post-decode image batch ops."""

    def __init__(self, n_threads: Optional[int] = None):
        self.n_threads = n_threads or min(8, os.cpu_count() or 1)

    def to_float(self, batch_u8: np.ndarray, scale: float = 1.0 / 255.0,
                 shift: float = 0.0) -> np.ndarray:
        batch_u8 = np.ascontiguousarray(batch_u8, np.uint8)
        out = np.empty(batch_u8.shape, np.float32)
        lib = get_lib()
        if lib is not None:
            lib.u8_to_f32(_fp(batch_u8, ctypes.POINTER(ctypes.c_uint8)),
                          _fp(out, ctypes.POINTER(ctypes.c_float)),
                          batch_u8.size, scale, shift, self.n_threads)
            return out
        return batch_u8.astype(np.float32) * scale + shift

    def normalize(self, batch_u8: np.ndarray, mean, std) -> np.ndarray:
        """(..., C) uint8 -> float32 (x/255 - mean)/std per channel."""
        batch_u8 = np.ascontiguousarray(batch_u8, np.uint8)
        c = batch_u8.shape[-1]
        mean = np.ascontiguousarray(mean, np.float32)
        std = np.ascontiguousarray(std, np.float32)
        out = np.empty(batch_u8.shape, np.float32)
        lib = get_lib()
        if lib is not None:
            lib.normalize_nhwc(_fp(batch_u8, ctypes.POINTER(ctypes.c_uint8)),
                               _fp(out, ctypes.POINTER(ctypes.c_float)),
                               batch_u8.size // c, c,
                               _fp(mean, ctypes.POINTER(ctypes.c_float)),
                               _fp(std, ctypes.POINTER(ctypes.c_float)))
            return out
        return (batch_u8.astype(np.float32) / 255.0 - mean) / std

    def random_crop_flip(self, batch_u8: np.ndarray, out_h: int, out_w: int,
                         seed: int = 0, flip: bool = True) -> np.ndarray:
        """(B, H, W, C) uint8 -> (B, out_h, out_w, C) uint8, deterministic
        per (seed, image-index)."""
        batch_u8 = np.ascontiguousarray(batch_u8, np.uint8)
        b, h, w, c = batch_u8.shape
        out = np.empty((b, out_h, out_w, c), np.uint8)
        lib = get_lib()
        if lib is not None:
            lib.random_crop_flip_batch(
                _fp(batch_u8, ctypes.POINTER(ctypes.c_uint8)),
                _fp(out, ctypes.POINTER(ctypes.c_uint8)),
                b, h, w, out_h, out_w, c, seed, int(flip), self.n_threads)
            return out
        rng = np.random.default_rng(seed)
        for i in range(b):
            oy = rng.integers(0, h - out_h + 1) if h > out_h else 0
            ox = rng.integers(0, w - out_w + 1) if w > out_w else 0
            img = batch_u8[i, oy:oy + out_h, ox:ox + out_w]
            if flip and rng.integers(0, 2):
                img = img[:, ::-1]
            out[i] = img
        return out


# --------------------------------------------------------------- C API build
_CAPI_LIB = os.path.join(_DIR, "libdl4jtpu_capi.so")


def build_capi(force: bool = False) -> Optional[str]:
    """Build the embedding C API (capi.cpp + dl4j_tpu_c.h): the language-
    bindings surface for C/C++ host applications (reference [U] jumpy/
    pydl4j/ nd4s — direction inverted, see dl4j_tpu_c.h). Returns the .so
    path, or None when no toolchain/libpython is available."""
    import sysconfig
    src = os.path.join(_DIR, "capi.cpp")
    hdr = os.path.join(_DIR, "dl4j_tpu_c.h")
    with _lock:
        if force and os.path.exists(_CAPI_LIB):
            os.unlink(_CAPI_LIB)
        inc = sysconfig.get_paths()["include"]
        libdir = sysconfig.get_config_var("LIBDIR") or ""
        ver = sysconfig.get_config_var("LDVERSION") or "3"
        return _compile(
            [src], _CAPI_LIB, headers=[hdr], march_native=False,
            extra_flags=[f"-I{inc}", f"-L{libdir}", f"-Wl,-rpath,{libdir}",
                         f"-lpython{ver}"])
