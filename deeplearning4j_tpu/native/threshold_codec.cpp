// Threshold gradient codec — native host component.
//
// Re-implementation of the reference's threshold-encoding wire format
// (libnd4j compression kernels: encodeThreshold/decodeThreshold — SURVEY.md
// §2.1 "Threshold encoding kernels"): values with |v| >= threshold are
// encoded as sign-tagged int32 indices, the un-sent remainder accumulates in
// a residual buffer. On-TPU DP uses dense psum over ICI (compression is a
// non-goal there), but the codec stays relevant for the DCN/multi-slice path
// and for parity with the reference's SharedTrainingMaster format.
//
// Encoding: out[0] = count; out[1..count] = (index + 1) with sign bit from
// the value's sign (negative index => negative value), matching the
// sparse-sign scheme. Residual update is fused into the encode pass.

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Encode with residual accumulation. Returns number of encoded indices
// (capped at max_elements). grad is left untouched; residual is updated:
//   acc = grad + residual
//   if |acc| >= t: emit sign(acc)*t, residual = acc - sign(acc)*t
//   else:          residual = acc
int64_t threshold_encode(const float* grad, float* residual, int64_t n,
                         float threshold, int32_t* out, int64_t max_elements) {
  int64_t count = 0;
  for (int64_t i = 0; i < n; ++i) {
    float acc = grad[i] + residual[i];
    if (acc >= threshold && count < max_elements) {
      out[count++] = static_cast<int32_t>(i + 1);
      residual[i] = acc - threshold;
    } else if (acc <= -threshold && count < max_elements) {
      out[count++] = -static_cast<int32_t>(i + 1);
      residual[i] = acc + threshold;
    } else {
      residual[i] = acc;
    }
  }
  return count;
}

// Would-be-emitted element count for (grad + residual) against threshold,
// WITHOUT touching the residual — the sparse-vs-bitmap format predictor
// (the choice must precede encoding: encoding is stateful).
int64_t threshold_count(const float* grad, const float* residual, int64_t n,
                        float threshold) {
  int64_t count = 0;
  for (int64_t i = 0; i < n; ++i) {
    float acc = grad[i] + residual[i];
    if (acc >= threshold || acc <= -threshold) ++count;
  }
  return count;
}

// Decode: target[|idx|-1] += sign(idx) * threshold
void threshold_decode(const int32_t* encoded, int64_t count, float threshold,
                      float* target, int64_t n) {
  for (int64_t i = 0; i < count; ++i) {
    int32_t idx = encoded[i];
    if (idx > 0 && idx <= n) {
      target[idx - 1] += threshold;
    } else if (idx < 0 && -idx <= n) {
      target[-idx - 1] -= threshold;
    }
  }
}

// Bitmap encoding (reference encodeBitmap): 2 bits per element
// (0 = skip, 1 = +threshold, 2 = -threshold). Returns bytes written.
int64_t bitmap_encode(const float* grad, float* residual, int64_t n,
                      float threshold, uint8_t* out) {
  int64_t nbytes = (n + 3) / 4;
  std::memset(out, 0, nbytes);
  for (int64_t i = 0; i < n; ++i) {
    float acc = grad[i] + residual[i];
    uint8_t code = 0;
    if (acc >= threshold) {
      code = 1;
      residual[i] = acc - threshold;
    } else if (acc <= -threshold) {
      code = 2;
      residual[i] = acc + threshold;
    } else {
      residual[i] = acc;
    }
    out[i >> 2] |= code << ((i & 3) * 2);
  }
  return nbytes;
}

void bitmap_decode(const uint8_t* encoded, int64_t n, float threshold,
                   float* target) {
  for (int64_t i = 0; i < n; ++i) {
    uint8_t code = (encoded[i >> 2] >> ((i & 3) * 2)) & 3;
    if (code == 1) target[i] += threshold;
    else if (code == 2) target[i] -= threshold;
  }
}

}  // extern "C"
