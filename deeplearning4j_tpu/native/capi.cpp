// C API implementation: embeds CPython and drives deeplearning4j_tpu.
// See dl4j_tpu_c.h for the contract and the parity rationale (reference
// language bindings [U] jumpy/ pydl4j/ nd4s/ — direction inverted because
// this framework's core is Python/JAX).

#include "dl4j_tpu_c.h"

#include <Python.h>

#include <cmath>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>

namespace {

std::mutex g_mutex;
std::map<int, PyObject *> g_models;  // handle -> network object
int g_next_handle = 0;
std::string g_last_error = "";
bool g_initialized = false;

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *trace = nullptr;
  PyErr_Fetch(&type, &value, &trace);
  PyErr_NormalizeException(&type, &value, &trace);
  g_last_error = "unknown python error";
  if (value) {
    PyObject *s = PyObject_Str(value);
    if (s) {
      const char *c = PyUnicode_AsUTF8(s);
      if (c) g_last_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(trace);
}

// Build a numpy f32 array that COPIES from the caller's buffer.
PyObject *np_from_buffer(const float *data, const int64_t *shape, int rank) {
  PyObject *np = PyImport_ImportModule("numpy");
  if (!np) return nullptr;
  int64_t n = 1;
  for (int i = 0; i < rank; ++i) n *= shape[i];
  PyObject *mv = PyMemoryView_FromMemory(
      reinterpret_cast<char *>(const_cast<float *>(data)),
      n * sizeof(float), PyBUF_READ);
  PyObject *arr = nullptr, *shaped = nullptr;
  if (mv) {
    // frombuffer gives a read-only view; .reshape().copy() detaches it
    PyObject *flat = PyObject_CallMethod(np, "frombuffer", "Os", mv, "float32");
    if (flat) {
      PyObject *dims = PyTuple_New(rank);
      for (int i = 0; i < rank; ++i)
        PyTuple_SET_ITEM(dims, i, PyLong_FromLongLong(shape[i]));
      shaped = PyObject_CallMethod(flat, "reshape", "O", dims);
      if (shaped) arr = PyObject_CallMethod(shaped, "copy", nullptr);
      Py_XDECREF(shaped);
      Py_DECREF(dims);
      Py_DECREF(flat);
    }
    Py_DECREF(mv);
  }
  Py_DECREF(np);
  return arr;  // may be nullptr with a python error set
}

PyObject *get_model(int handle) {
  auto it = g_models.find(handle);
  if (it == g_models.end()) {
    g_last_error = "invalid model handle";
    return nullptr;
  }
  return it->second;
}

}  // namespace

extern "C" {

int dl4jtpu_init(const char *repo_path) {
  std::lock_guard<std::mutex> lock(g_mutex);
  if (g_initialized) return 0;
  // Sticky across retries: a failed first init (bad repo_path) must not
  // make a later successful call forget that WE created the interpreter.
  static bool g_we_initialized = false;
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    g_we_initialized = true;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = 0;
  if (repo_path != nullptr) {
    PyObject *sys = PyImport_ImportModule("sys");
    PyObject *path = sys ? PyObject_GetAttrString(sys, "path") : nullptr;
    PyObject *p = path ? PyUnicode_FromString(repo_path) : nullptr;
    if (p) PyList_Insert(path, 0, p);
    Py_XDECREF(p);
    Py_XDECREF(path);
    Py_XDECREF(sys);
  }
  PyObject *mod = PyImport_ImportModule("deeplearning4j_tpu.models.serializer");
  if (!mod) {
    set_error_from_python();
    rc = -1;
  } else {
    Py_DECREF(mod);
    g_initialized = true;
  }
  PyGILState_Release(gil);
  if (g_we_initialized) {
    // Py_InitializeEx leaves THIS thread holding the GIL; release it so
    // other host threads' PyGILState_Ensure calls can proceed (the
    // header promises any-thread calls). Done even when rc != 0 — a
    // failed import must not leave the GIL parked on this thread. Only
    // done when THIS library initialized the interpreter: a host that
    // pre-initialized Python and calls dl4jtpu_init while holding the
    // GIL keeps it (releasing it behind the host's back would break its
    // own Python API use).
    static PyThreadState *g_main_tstate = nullptr;
    if (g_main_tstate == nullptr && PyGILState_Check())
      g_main_tstate = PyEval_SaveThread();
    (void)g_main_tstate;
  }
  return rc;
}

int dl4jtpu_load(const char *model_path) {
  std::lock_guard<std::mutex> lock(g_mutex);
  if (!g_initialized) {
    g_last_error = "dl4jtpu_init was not called";
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int handle = -1;
  PyObject *mod = PyImport_ImportModule("deeplearning4j_tpu.models.serializer");
  PyObject *ser = mod ? PyObject_GetAttrString(mod, "ModelSerializer") : nullptr;
  PyObject *net = ser ? PyObject_CallMethod(ser, "restore_model", "s", model_path)
                      : nullptr;
  if (net) {
    handle = g_next_handle++;
    g_models[handle] = net;  // keep the reference
  } else {
    set_error_from_python();
  }
  Py_XDECREF(ser);
  Py_XDECREF(mod);
  PyGILState_Release(gil);
  return handle;
}

int64_t dl4jtpu_output(int handle, const float *data, const int64_t *shape,
                       int rank, float *out, int64_t out_capacity,
                       int64_t *out_shape, int *out_rank) {
  std::lock_guard<std::mutex> lock(g_mutex);
  PyGILState_STATE gil = PyGILState_Ensure();
  int64_t total = -1;
  do {
    PyObject *net = get_model(handle);
    if (!net) break;
    PyObject *x = np_from_buffer(data, shape, rank);
    if (!x) { set_error_from_python(); break; }
    PyObject *pred = PyObject_CallMethod(net, "output", "O", x);
    Py_DECREF(x);
    if (!pred) { set_error_from_python(); break; }
    // ComputationGraph.output returns a list of outputs; take the first
    if (PyList_Check(pred) || PyTuple_Check(pred)) {
      PyObject *first = PySequence_GetItem(pred, 0);
      Py_DECREF(pred);
      pred = first;
      if (!pred) { set_error_from_python(); break; }
    }
    PyObject *np = PyImport_ImportModule("numpy");
    PyObject *arr = np ? PyObject_CallMethod(np, "ascontiguousarray", "Os",
                                             pred, "float32")
                       : nullptr;
    Py_XDECREF(np);
    Py_DECREF(pred);
    if (!arr) { set_error_from_python(); break; }
    Py_buffer view;
    if (PyObject_GetBuffer(arr, &view, PyBUF_CONTIG_RO | PyBUF_FORMAT) != 0) {
      set_error_from_python();
      Py_DECREF(arr);
      break;
    }
    total = static_cast<int64_t>(view.len / sizeof(float));
    int64_t ncopy = total < out_capacity ? total : out_capacity;
    if (out != nullptr && ncopy > 0)
      memcpy(out, view.buf, ncopy * sizeof(float));
    if (out_shape != nullptr && out_rank != nullptr) {
      *out_rank = view.ndim <= 8 ? view.ndim : 8;
      for (int i = 0; i < *out_rank; ++i) out_shape[i] = view.shape[i];
    }
    PyBuffer_Release(&view);
    Py_DECREF(arr);
  } while (false);
  PyGILState_Release(gil);
  return total;
}

double dl4jtpu_fit(int handle, const float *x, const int64_t *xshape,
                   int xrank, const float *y, const int64_t *yshape,
                   int yrank) {
  std::lock_guard<std::mutex> lock(g_mutex);
  PyGILState_STATE gil = PyGILState_Ensure();
  double score = std::nan("");
  do {
    PyObject *net = get_model(handle);
    if (!net) break;
    PyObject *xa = np_from_buffer(x, xshape, xrank);
    PyObject *ya = xa ? np_from_buffer(y, yshape, yrank) : nullptr;
    PyObject *r = ya ? PyObject_CallMethod(net, "fit", "OO", xa, ya) : nullptr;
    Py_XDECREF(xa);
    Py_XDECREF(ya);
    if (!r) { set_error_from_python(); break; }
    Py_DECREF(r);
    PyObject *s = PyObject_CallMethod(net, "score", nullptr);
    if (s) {
      score = PyFloat_AsDouble(s);
      Py_DECREF(s);
      if (PyErr_Occurred()) { set_error_from_python(); score = std::nan(""); }
    } else {
      set_error_from_python();
    }
  } while (false);
  PyGILState_Release(gil);
  return score;
}

int dl4jtpu_save(int handle, const char *model_path) {
  std::lock_guard<std::mutex> lock(g_mutex);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  do {
    PyObject *net = get_model(handle);
    if (!net) break;
    PyObject *mod = PyImport_ImportModule("deeplearning4j_tpu.models.serializer");
    PyObject *ser = mod ? PyObject_GetAttrString(mod, "ModelSerializer") : nullptr;
    PyObject *r = ser ? PyObject_CallMethod(ser, "write_model", "Os", net,
                                            model_path)
                      : nullptr;
    Py_XDECREF(ser);
    Py_XDECREF(mod);
    if (!r) { set_error_from_python(); break; }
    Py_DECREF(r);
    rc = 0;
  } while (false);
  PyGILState_Release(gil);
  return rc;
}

void dl4jtpu_close(int handle) {
  std::lock_guard<std::mutex> lock(g_mutex);
  auto it = g_models.find(handle);
  if (it != g_models.end()) {
    PyGILState_STATE gil = PyGILState_Ensure();
    Py_DECREF(it->second);
    PyGILState_Release(gil);
    g_models.erase(it);
  }
}

void dl4jtpu_last_error(char *buf, int64_t buflen) {
  std::lock_guard<std::mutex> lock(g_mutex);
  if (buf == nullptr || buflen <= 0) return;
  snprintf(buf, static_cast<size_t>(buflen), "%s", g_last_error.c_str());
}

void dl4jtpu_shutdown(void) {
  std::lock_guard<std::mutex> lock(g_mutex);
  if (!g_initialized) return;
  PyGILState_STATE gil = PyGILState_Ensure();
  for (auto &kv : g_models) Py_DECREF(kv.second);
  g_models.clear();
  PyGILState_Release(gil);
  // Finalizing an embedded interpreter with live jax/XLA state can hang;
  // leave the runtime alive for the process lifetime (standard practice
  // for embedded ML runtimes).
  g_initialized = false;
}

}  // extern "C"
