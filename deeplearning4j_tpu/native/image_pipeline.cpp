// Host-side image batch pipeline — native data-loader component.
//
// The role the reference fills with NativeImageLoader + OpenCV (SURVEY.md
// §2.1 "Native image loader"): the host-bound inner loops of the input
// pipeline — uint8 -> float conversion with normalization, random
// crop + horizontal flip augmentation, NHWC assembly — multithreaded C++ so
// the TPU feed path is not bottlenecked on Python byte shuffling. JPEG
// entropy decode itself is delegated to the bundled TF op (already native);
// this library covers everything after decode.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

inline uint64_t next_rand(uint64_t* state) {
  // xorshift64* — deterministic per-seed augmentation
  uint64_t x = *state;
  x ^= x >> 12;
  x ^= x << 25;
  x ^= x >> 27;
  *state = x;
  return x * 0x2545F4914F6CDD1DULL;
}

void convert_range(const uint8_t* in, float* out, int64_t start, int64_t end,
                   float scale, float shift) {
  for (int64_t i = start; i < end; ++i) {
    out[i] = static_cast<float>(in[i]) * scale + shift;
  }
}

}  // namespace

extern "C" {

// uint8 -> float32 with y = x * scale + shift, multithreaded.
void u8_to_f32(const uint8_t* in, float* out, int64_t n, float scale,
               float shift, int32_t n_threads) {
  if (n_threads <= 1 || n < (1 << 16)) {
    convert_range(in, out, 0, n, scale, shift);
    return;
  }
  std::vector<std::thread> threads;
  int64_t chunk = (n + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; ++t) {
    int64_t s = t * chunk;
    int64_t e = std::min(n, s + chunk);
    if (s >= e) break;
    threads.emplace_back(convert_range, in, out, s, e, scale, shift);
  }
  for (auto& th : threads) th.join();
}

// Per-channel mean/std normalize: out = (in*(1/255) - mean[c]) / std[c].
// NHWC layout; in is uint8.
void normalize_nhwc(const uint8_t* in, float* out, int64_t n_pixels,
                    int32_t channels, const float* mean, const float* stddev) {
  for (int64_t p = 0; p < n_pixels; ++p) {
    const uint8_t* src = in + p * channels;
    float* dst = out + p * channels;
    for (int32_t c = 0; c < channels; ++c) {
      dst[c] = (static_cast<float>(src[c]) / 255.0f - mean[c]) / stddev[c];
    }
  }
}

// Random crop + optional horizontal flip for a whole batch.
// in:  (batch, in_h, in_w, c) uint8; out: (batch, out_h, out_w, c) uint8.
// One xorshift stream per image derived from seed + index (deterministic,
// order-independent — reproducible under any loader threading).
void random_crop_flip_batch(const uint8_t* in, uint8_t* out, int32_t batch,
                            int32_t in_h, int32_t in_w, int32_t out_h,
                            int32_t out_w, int32_t c, uint64_t seed,
                            int32_t do_flip, int32_t n_threads) {
  auto work = [&](int32_t b0, int32_t b1) {
    for (int32_t b = b0; b < b1; ++b) {
      uint64_t state = seed + 0x9E3779B97F4A7C15ULL * (b + 1);
      next_rand(&state);
      int32_t max_y = in_h - out_h;
      int32_t max_x = in_w - out_w;
      int32_t oy = max_y > 0 ? static_cast<int32_t>(next_rand(&state) % (max_y + 1)) : 0;
      int32_t ox = max_x > 0 ? static_cast<int32_t>(next_rand(&state) % (max_x + 1)) : 0;
      bool flip = do_flip && (next_rand(&state) & 1);
      const uint8_t* src_img = in + static_cast<int64_t>(b) * in_h * in_w * c;
      uint8_t* dst_img = out + static_cast<int64_t>(b) * out_h * out_w * c;
      for (int32_t y = 0; y < out_h; ++y) {
        const uint8_t* src_row = src_img + (static_cast<int64_t>(y + oy) * in_w + ox) * c;
        uint8_t* dst_row = dst_img + static_cast<int64_t>(y) * out_w * c;
        if (!flip) {
          std::memcpy(dst_row, src_row, static_cast<size_t>(out_w) * c);
        } else {
          for (int32_t x = 0; x < out_w; ++x) {
            std::memcpy(dst_row + static_cast<int64_t>(x) * c,
                        src_row + static_cast<int64_t>(out_w - 1 - x) * c, c);
          }
        }
      }
    }
  };
  if (n_threads <= 1 || batch < 4) {
    work(0, batch);
    return;
  }
  std::vector<std::thread> threads;
  int32_t chunk = (batch + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; ++t) {
    int32_t s = t * chunk;
    int32_t e = std::min(batch, s + chunk);
    if (s >= e) break;
    threads.emplace_back(work, s, e);
  }
  for (auto& th : threads) th.join();
}

}  // extern "C"
