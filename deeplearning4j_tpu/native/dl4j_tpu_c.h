/* C API for deeplearning4j_tpu — language bindings for non-Python hosts.
 *
 * The reference shipped language bindings as bridges into its JVM core
 * (jumpy / pydl4j: Python -> JVM via JNI; nd4s: Scala sugar — upstream
 * [U] jumpy/, pydl4j/, nd4s/). This framework's core is Python/JAX, so the
 * binding direction inverts: a C/C++ host application embeds the Python
 * runtime and drives models through this flat C surface (load, predict,
 * fit). Same capability row, TPU-era direction.
 *
 * Thread-safety: calls may come from any thread; each entry point takes
 * the GIL. Heavy compute releases it inside JAX as usual.
 *
 * Build: see deeplearning4j_tpu/native/__init__.py::build_capi (g++,
 * links libpython). A minimal host program:
 *
 *   dl4jtpu_init(NULL);
 *   int h = dl4jtpu_load("model.zip");
 *   int64_t shape[2] = {1, 784};
 *   float out[10];
 *   int64_t n = dl4jtpu_output(h, x, shape, 2, out, 10, NULL, NULL);
 *   dl4jtpu_close(h);
 *   dl4jtpu_shutdown();
 */
#ifndef DL4J_TPU_C_H
#define DL4J_TPU_C_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* Initialise the embedded Python runtime and import the framework.
 * repo_path: directory to prepend to sys.path (NULL = rely on PYTHONPATH).
 * Returns 0 on success, -1 on failure (see dl4jtpu_last_error).
 * If the host application already initialised CPython, its interpreter is
 * reused and its GIL state is left exactly as found (this library only
 * releases the GIL after init when it created the interpreter itself). */
int dl4jtpu_init(const char *repo_path);

/* Load a ModelSerializer zip (MultiLayerNetwork or ComputationGraph).
 * Returns a handle >= 0, or -1 on failure. */
int dl4jtpu_load(const char *model_path);

/* Forward pass. data: row-major f32 input of the given shape.
 * Writes up to out_capacity floats of the (first) network output into out;
 * returns the number of floats the full output has, or -1 on failure.
 * out_shape (optional, may be NULL): receives up to 8 output dims,
 * out_rank the dim count. */
int64_t dl4jtpu_output(int handle, const float *data, const int64_t *shape,
                       int rank, float *out, int64_t out_capacity,
                       int64_t *out_shape, int *out_rank);

/* One fit batch (features + one-hot/regression labels, both f32
 * row-major). Returns the score (loss) after the step, or NaN on failure. */
double dl4jtpu_fit(int handle, const float *x, const int64_t *xshape,
                   int xrank, const float *y, const int64_t *yshape,
                   int yrank);

/* Save the model back to a ModelSerializer zip. 0 on success. */
int dl4jtpu_save(int handle, const char *model_path);

/* Release a model handle. */
void dl4jtpu_close(int handle);

/* Copy the last error message (UTF-8, NUL-terminated) into buf. */
void dl4jtpu_last_error(char *buf, int64_t buflen);

/* Finalise the embedded interpreter. */
void dl4jtpu_shutdown(void);

#ifdef __cplusplus
}
#endif

#endif /* DL4J_TPU_C_H */
