"""deeplearning4j_tpu — a TPU-native deep-learning framework.

A ground-up rebuild of the capabilities of Deeplearning4j (reference:
EronWright/deeplearning4j) designed for TPU hardware: every training step is a
single jitted XLA program over a donated state pytree; parallelism is expressed
with `jax.sharding` meshes instead of parameter servers; hot ops beyond XLA's
fusions are Pallas kernels.

Top-level layout (mirrors SURVEY.md §2's component inventory):

- ``runtime``   — device/mesh discovery, dtype policy, RNG, runtime config
                  facade, profiling hooks (reference: nd4j runtime config +
                  ``OpProfiler``).
- ``ops``       — activations, losses, initializers, and Pallas TPU kernels
                  (reference: libnd4j loops + declarable ops; cuDNN helpers).
- ``nn``        — config-as-data layer DSL with ``InputType`` shape inference
                  (reference: ``org.deeplearning4j.nn.conf``).
- ``models``    — ``MultiLayerNetwork`` / ``ComputationGraph`` equivalents plus
                  ``ModelSerializer`` (reference: ``org.deeplearning4j.nn``).
- ``train``     — updaters, LR schedules, listeners, the jitted training engine
                  (reference: ``org.deeplearning4j.optimize`` + nd4j updaters).
- ``evaluation``— ``Evaluation`` / ``ROC`` / ``RegressionEvaluation``
                  (reference: ``org.nd4j.evaluation``).
- ``data``      — DataSet/iterators/normalizers + DataVec-style ETL
                  (reference: datavec + dl4j-data).
- ``autodiff``  — SameDiff-equivalent declarative graph API
                  (reference: ``org.nd4j.autodiff.samediff``).
- ``imports``   — Keras-H5 / TF-GraphDef model import (reference:
                  ``org.deeplearning4j.nn.modelimport``, ``org.nd4j.imports``).
- ``parallel``  — mesh sharding (DP/TP/FSDP/SP), ParallelInference, multi-host
                  (reference: ParallelWrapper, dl4j-spark, nd4j-parameter-server).
- ``serving``   — production model serving: registry with hot-swap, shape-
                  bucketed continuous batcher, admission control, HTTP front
                  end, SLO metrics (reference: ParallelInference + the
                  konduit/dl4j model-server layer).
- ``zoo``       — model zoo (reference: ``org.deeplearning4j.zoo``).
- ``nlp``       — Word2Vec & friends (reference: deeplearning4j-nlp).
- ``ui``        — stats collection/serving (reference: deeplearning4j-ui).
"""

__version__ = "0.1.0"

# Opt-in runtime lock-order witness (ISSUE 14). MUST run before any other
# package import so module-level locks are constructed through the
# patched factories; fleet worker subprocesses inherit the env var, so a
# drill's whole process tree is witnessed. No-op unless DL4J_TPU_LOCKDEP=1.
from deeplearning4j_tpu.analysis import lockdep as _lockdep
_lockdep.enable_from_env()

from deeplearning4j_tpu.runtime import environment as _environment  # noqa: F401
