"""Parameter spaces (reference
``org.deeplearning4j.arbiter.optimize.parameter.*``)."""

from __future__ import annotations

import math
from typing import Any, List, Sequence

import numpy as np


class ParameterSpace:
    def sample(self, rng: np.random.Generator) -> Any:
        raise NotImplementedError

    def grid_values(self, n: int) -> List[Any]:
        raise NotImplementedError


class ContinuousParameterSpace(ParameterSpace):
    def __init__(self, low: float, high: float, log_scale: bool = False):
        self.low, self.high, self.log_scale = float(low), float(high), log_scale

    def sample(self, rng):
        if self.log_scale:
            return float(math.exp(rng.uniform(math.log(self.low), math.log(self.high))))
        return float(rng.uniform(self.low, self.high))

    def grid_values(self, n):
        if self.log_scale:
            return [float(v) for v in np.geomspace(self.low, self.high, n)]
        return [float(v) for v in np.linspace(self.low, self.high, n)]


class IntegerParameterSpace(ParameterSpace):
    def __init__(self, low: int, high: int):
        self.low, self.high = int(low), int(high)

    def sample(self, rng):
        return int(rng.integers(self.low, self.high + 1))

    def grid_values(self, n):
        return sorted({int(v) for v in np.linspace(self.low, self.high, n)})


class DiscreteParameterSpace(ParameterSpace):
    def __init__(self, *values: Any):
        self.values = list(values)

    def sample(self, rng):
        return self.values[int(rng.integers(0, len(self.values)))]

    def grid_values(self, n):
        return list(self.values)
