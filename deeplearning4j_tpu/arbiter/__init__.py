"""Hyperparameter optimization (Arbiter equivalent).

Rebuild of upstream ``arbiter`` (``org.deeplearning4j.arbiter``): parameter
spaces, random/grid candidate generation, a local optimization runner with
score functions and result tracking. The reference parameterises its conf
builders with ``ParameterSpace<T>`` fields; here a candidate is a plain dict
sampled from named spaces and handed to a user config factory — same
search loop, configs stay data.

Usage::

    space = {
        "lr": ContinuousParameterSpace(1e-4, 1e-1, log_scale=True),
        "hidden": DiscreteParameterSpace(32, 64, 128),
    }
    def factory(c):
        return (NeuralNetConfiguration.builder().updater(Adam(c["lr"])).list()
                .layer(DenseLayer(n_out=c["hidden"], activation="relu"))
                .layer(OutputLayer(n_out=3, activation="softmax"))
                .set_input_type(InputType.feed_forward(4)).build())
    runner = LocalOptimizationRunner(
        factory, space, RandomSearchGenerator(16, seed=1),
        score_function=EvaluationScoreFunction("accuracy"),
        train_iterator=train_it, eval_iterator=test_it, epochs=3)
    best = runner.execute()
"""

from deeplearning4j_tpu.arbiter.spaces import (
    ContinuousParameterSpace,
    DiscreteParameterSpace,
    IntegerParameterSpace,
)
from deeplearning4j_tpu.arbiter.runner import (
    EvaluationScoreFunction,
    GridSearchGenerator,
    LocalOptimizationRunner,
    LossScoreFunction,
    OptimizationResult,
    RandomSearchGenerator,
)

__all__ = [
    "ContinuousParameterSpace", "DiscreteParameterSpace", "IntegerParameterSpace",
    "RandomSearchGenerator", "GridSearchGenerator", "LocalOptimizationRunner",
    "EvaluationScoreFunction", "LossScoreFunction", "OptimizationResult",
]
