"""Optimization runner (reference
``org.deeplearning4j.arbiter.optimize.runner.LocalOptimizationRunner`` +
candidate generators + score functions)."""

from __future__ import annotations

import dataclasses
import itertools
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.arbiter.spaces import ParameterSpace


class CandidateGenerator:
    def candidates(self, spaces: Dict[str, ParameterSpace]):
        raise NotImplementedError


class RandomSearchGenerator(CandidateGenerator):
    def __init__(self, num_candidates: int, seed: int = 0):
        self.num_candidates = int(num_candidates)
        self.seed = seed

    def candidates(self, spaces):
        rng = np.random.default_rng(self.seed)
        for _ in range(self.num_candidates):
            yield {k: s.sample(rng) for k, s in spaces.items()}


class GridSearchGenerator(CandidateGenerator):
    def __init__(self, discretization: int = 4):
        self.discretization = discretization

    def candidates(self, spaces):
        keys = list(spaces.keys())
        grids = [spaces[k].grid_values(self.discretization) for k in keys]
        for combo in itertools.product(*grids):
            yield dict(zip(keys, combo))


class ScoreFunction:
    minimize = True

    def score(self, model, eval_iterator) -> float:
        raise NotImplementedError


class EvaluationScoreFunction(ScoreFunction):
    """Score = classification metric on the eval iterator (maximized)."""

    minimize = False

    def __init__(self, metric: str = "accuracy"):
        self.metric = metric

    def score(self, model, eval_iterator):
        ev = model.evaluate(eval_iterator)
        return float(getattr(ev, self.metric)())


class LossScoreFunction(ScoreFunction):
    """Score = average loss over the eval iterator (minimized)."""

    minimize = True

    def score(self, model, eval_iterator):
        eval_iterator.reset()
        losses = [model.score(b) for b in eval_iterator]
        return float(np.mean(losses))


class LoadedResults(list):
    """Result list plus the persisted minimize/maximize direction, so
    ``best()`` can be recomputed from the file alone."""

    def __init__(self, results, minimize: bool):
        super().__init__(results)
        self.minimize = minimize

    def best(self):
        key = min if self.minimize else max
        return key(self, key=lambda r: r.score) if self else None


@dataclasses.dataclass
class OptimizationResult:
    index: int
    candidate: Dict[str, Any]
    score: float
    duration_s: float
    model: Any = None


class LocalOptimizationRunner:
    def __init__(self, config_factory: Callable[[Dict[str, Any]], Any],
                 spaces: Dict[str, ParameterSpace],
                 generator: CandidateGenerator,
                 score_function: ScoreFunction,
                 train_iterator, eval_iterator,
                 epochs: int = 1, keep_models: bool = False,
                 listeners: Optional[List[Callable]] = None):
        self.config_factory = config_factory
        self.spaces = spaces
        self.generator = generator
        self.score_function = score_function
        self.train_iterator = train_iterator
        self.eval_iterator = eval_iterator
        self.epochs = epochs
        self.keep_models = keep_models
        self.listeners = listeners or []
        self.results: List[OptimizationResult] = []

    def execute(self) -> OptimizationResult:
        """Run all candidates; returns the best result (all results in
        ``self.results``)."""
        from deeplearning4j_tpu.models.computation_graph import (
            ComputationGraph, ComputationGraphConfiguration)
        from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
        best: Optional[OptimizationResult] = None
        for i, candidate in enumerate(self.generator.candidates(self.spaces)):
            t0 = time.perf_counter()
            conf = self.config_factory(candidate)
            if isinstance(conf, ComputationGraphConfiguration):
                model = ComputationGraph(conf).init()
            else:
                model = MultiLayerNetwork(conf).init()
            self.train_iterator.reset()
            model.fit(self.train_iterator, epochs=self.epochs)
            score = self.score_function.score(model, self.eval_iterator)
            res = OptimizationResult(
                index=i, candidate=candidate, score=score,
                duration_s=time.perf_counter() - t0,
                model=model if self.keep_models else None)
            self.results.append(res)
            for lst in self.listeners:
                lst(res)
            better = (best is None
                      or (score < best.score if self.score_function.minimize
                          else score > best.score))
            if better:
                best = res
        return best

    def best_result(self) -> Optional[OptimizationResult]:
        if not self.results:
            return None
        key = (min if self.score_function.minimize else max)
        return key(self.results, key=lambda r: r.score)

    # ---- result persistence (reference arbiter's ResultSaver) ----
    def save_results(self, path: str) -> None:
        """Write all candidate results as JSON (models are not serialized
        here — save the best model separately via its own ``save``)."""
        import json
        recs = [{"index": r.index, "score": float(r.score),
                 "duration_s": float(r.duration_s),
                 "candidate": {k: (v if isinstance(v, (int, float, str, bool))
                                   else str(v))
                               for k, v in r.candidate.items()}}
                for r in self.results]
        with open(path, "w") as f:
            json.dump({"minimize": self.score_function.minimize,
                       "results": recs}, f, indent=1)

    @staticmethod
    def load_results(path: str) -> "LoadedResults":
        import json
        with open(path) as f:
            data = json.load(f)
        results = [OptimizationResult(index=r["index"], candidate=r["candidate"],
                                      score=r["score"],
                                      duration_s=r.get("duration_s", 0.0))
                   for r in data["results"]]
        return LoadedResults(results, bool(data.get("minimize", True)))
