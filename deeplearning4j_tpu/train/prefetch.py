"""Overlapped training feed path: device prefetch + async loss readback.

The synchronous fit loops (``MultiLayerNetwork._run_epochs``,
``ParallelWrapper.fit``) leave the device idle on every batch: host-side
ETL + ``jnp.asarray`` (and, sharded, the blocking ``shard_batch`` transfer)
run *between* steps, and listener delivery — which may read ``float(loss)``
and therefore sync on the device — runs *before* the next batch is even
fetched. ``AsyncDataSetIterator`` only overlaps host ETL; the host→device
leg and the loss readback stay on the critical path.

This module is the training-side analog of the serving pipeline
(``serving/batcher.py``, ISSUE 3): the feed path becomes explicit stages
that overlap with device execution, while the dispatch *order* — and with
it the rng-key sequence and the whole trajectory — stays exactly the
synchronous loop's, so results are bit-identical.

- :class:`DevicePrefetcher` — background stage that pulls from any
  ``DataSetIterator`` (composing with ``AsyncDataSetIterator`` for ETL),
  coerces the batch (``coerce_training_batch``) and issues the host→device
  transfer ahead of time, keeping up to ``prefetch_buffer`` batches staged
  while the current step executes. Bounded-queue backpressure; a
  ``train.prefetch.fetch`` chaos point per fetch; a worker fault surfaces
  on the consumer's next pull and ``close()`` never leaves a live thread.
- :class:`AsyncLossDelivery` — completion stage: listener delivery
  (``iteration_done``, ``PerformanceListener.record_batch``) moves to a
  single worker that preserves submission order and exact callback
  arguments but no longer blocks dispatch when a listener reads the score.
  Mirrors ``GroupedDispatch``'s snapshot-before-deliver discipline: items
  are snapshotted at submit, delivered FIFO, drained on every exit path.
- :func:`coerce_training_batch` — the one shared batch-coercion /
  mask-defaulting helper (previously duplicated between
  ``MultiLayerNetwork._run_epochs`` and ``ParallelWrapper._run_step``).

Only listeners that declare ``needs_model_state = False`` may be delivered
asynchronously: a state-reading listener must observe the post-step
``train_state`` of *its* iteration, which forces one-at-a-time dispatch
(the same gate ``PackedStepLoop.for_network`` applies to state packing).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterator, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.runtime import chaos

#: Queue tokens. ``_DONE`` ends a stream; never user data.
_DONE = object()
_STOP = object()

#: Chaos point fired once per fetched batch on the training feed path,
#: before coercion/transfer — in the prefetch worker when prefetching,
#: inline on the synchronous path, so one drill schedule covers both.
FETCH_POINT = "train.prefetch.fetch"


def stateless_listeners(model) -> bool:
    """True when every attached listener declares it never reads
    ``model.train_state`` — the gate for async loss readback (and the same
    condition state packing uses)."""
    return all(not getattr(l, "needs_model_state", True)
               for l in getattr(model, "_listeners", []))


def coerce_training_batch(model, batch):
    """Coerce a ``DataSet`` minibatch to step arguments ``(x, y, fm, lm)``.

    The labels mask defaults to the features mask propagated through any
    time-axis-changing layers (``model._output_time_mask``) for
    per-timestep labels — the reference's tBPTT/masking semantics. Shared
    by ``MultiLayerNetwork._run_epochs``, ``ParallelWrapper`` and
    :class:`DevicePrefetcher`; pure host→device work, safe off-thread.
    """
    x = jnp.asarray(batch.features)
    y = jnp.asarray(batch.labels)
    fm = None if batch.features_mask is None else jnp.asarray(batch.features_mask)
    lm = jnp.asarray(batch.labels_mask) if batch.labels_mask is not None \
        else (model._output_time_mask(fm) if y.ndim == 3 else None)
    return x, y, fm, lm


class _SyncBatchSource:
    """Degenerate source: fetch+coerce inline on the consumer thread —
    byte-for-byte the old synchronous loop, plus data-wait timing."""

    def __init__(self, iterator, prepare, profiler=None):
        self._iterator = iterator
        self._prepare = prepare
        self._profiler = profiler

    def __iter__(self) -> Iterator[Any]:
        # explicit reset BEFORE iterating, exactly as the old fit loops did:
        # not every iterator's __iter__ resets (the fault-tolerance fence
        # and skip wrappers iterate from their current position)
        self._iterator.reset()
        it = iter(self._iterator)
        while True:
            t0 = time.perf_counter() if self._profiler else 0.0
            try:
                ds = next(it)
            except StopIteration:
                return
            chaos.inject(FETCH_POINT)
            item = self._prepare(ds)
            if self._profiler:
                self._profiler.record_data_wait(time.perf_counter() - t0)
            yield item

    def close(self) -> None:
        pass


class DevicePrefetcher:
    """Background fetch/coerce/transfer stage over a ``DataSetIterator``.

    The worker thread iterates the base iterator (through the normal
    ``__iter__`` protocol, so ``reset()`` and ``pre_processor`` semantics
    are preserved), fires the ``train.prefetch.fetch`` chaos point, runs
    ``prepare(ds)`` — batch coercion plus the ahead-of-time
    ``jax.device_put`` (sharded via the strategy's ``NamedSharding``s under
    ``ParallelWrapper``) — and stages the result in a bounded queue of
    ``buffer`` batches. The consumer iterates in FIFO order, so the step
    sequence is exactly the synchronous loop's.

    A worker fault (iterator error, failed transfer, injected chaos)
    surfaces on the consumer's **next** pull — staged batches after the
    fault are discarded — and the worker exits. ``close()`` (every exit
    path must call it) stops the worker promptly even when it is blocked on
    a full queue, and closes the underlying iterator's own worker when it
    has one (``AsyncDataSetIterator.close``), so no thread outlives the
    fit that started it.
    """

    def __init__(self, iterator, prepare: Callable[[Any], Any],
                 buffer: int = 2, profiler=None, name: str = "train-prefetch"):
        self._iterator = iterator
        self._prepare = prepare
        self._profiler = profiler
        self._queue: queue.Queue = queue.Queue(maxsize=max(1, int(buffer)))
        self._stop = threading.Event()
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._worker, name=name,
                                        daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- worker
    def _worker(self) -> None:
        from deeplearning4j_tpu.data.iterators import stop_aware_put
        try:
            # explicit reset first (see _SyncBatchSource.__iter__): wrappers
            # like the fault-tolerance skip iterator only rewind on reset()
            self._iterator.reset()
            for ds in self._iterator:
                if self._stop.is_set():
                    return
                chaos.inject(FETCH_POINT)
                if not stop_aware_put(self._queue, self._prepare(ds),
                                      self._stop):
                    return
        except BaseException as e:  # surfaced on the consumer side
            self._error = e
        finally:
            stop_aware_put(self._queue, _DONE, self._stop)

    # ----------------------------------------------------------- consumer
    def _raise_pending(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def __iter__(self) -> Iterator[Any]:
        while True:
            # a fault that already happened surfaces NOW, before any batch
            # staged behind it — the fit fails at the fault, not after
            # training the tail of the buffer
            self._raise_pending()
            t0 = time.perf_counter() if self._profiler else 0.0
            item = self._queue.get()
            if self._profiler:
                self._profiler.record_data_wait(time.perf_counter() - t0)
            if item is _DONE:
                self._raise_pending()
                return
            yield item

    def close(self) -> None:
        """Stop the worker and join it; idempotent, called on every fit
        exit path (epoch end, fault, KeyboardInterrupt)."""
        from deeplearning4j_tpu.data.iterators import drain_and_join
        self._stop.set()
        drain_and_join(self._queue, self._thread)
        # a mid-stream close leaves a composed AsyncDataSetIterator's own
        # worker parked on ITS queue; shut it down too (reset() restarts it)
        closer = getattr(self._iterator, "close", None)
        if callable(closer):
            closer()


def batch_source(iterator, prepare, prefetch_buffer: int = 0, profiler=None,
                 name: str = "train-prefetch"):
    """The fit loops' one switch between the synchronous feed path and the
    staged pipeline: ``prefetch_buffer == 0`` fetches inline (bit-for-bit
    the old loop), ``> 0`` stages that many batches ahead."""
    if prefetch_buffer and int(prefetch_buffer) > 0:
        return DevicePrefetcher(iterator, prepare, buffer=int(prefetch_buffer),
                                profiler=profiler, name=name)
    return _SyncBatchSource(iterator, prepare, profiler=profiler)


class AsyncLossDelivery:
    """Completion-path listener delivery (single worker, FIFO).

    ``submit(args, loss)`` snapshots the step's bookkeeping arguments and
    returns immediately; the worker calls ``deliver(args, loss)`` — the fit
    loop's existing score/iteration/listener bookkeeping — in submission
    order. A listener that reads ``float(loss)`` now syncs on the worker,
    not on the dispatch loop, so the next step is already in flight while
    the previous loss is read back.

    Submit only what deliver reads (the fit loops pass the batch SIZE, not
    the batch): queued items pin their payload for up to ``max_pending``
    deliveries, and holding full device batches there would retain memory
    the synchronous loop released after one step.

    Exact-semantics contract: same callbacks, same arguments, same order as
    the synchronous loop; only the thread (and hence *when* a listener
    exception surfaces) differs. A listener exception is recorded, later
    deliveries are skipped, and the error re-raises on the next
    ``submit``/``flush``/``raise_pending`` — ``fit`` drains on every exit
    path, so it never passes silently.
    """

    def __init__(self, deliver: Callable[[Any, Any], None], max_pending: int = 64,
                 profiler=None, name: str = "train-listener-delivery"):
        self._deliver = deliver
        self._profiler = profiler
        self._queue: queue.Queue = queue.Queue(maxsize=max(1, int(max_pending)))
        self._error: Optional[BaseException] = None
        self._closed = False
        self._thread = threading.Thread(target=self._worker, name=name,
                                        daemon=True)
        self._thread.start()

    def _worker(self) -> None:
        while True:
            item = self._queue.get()
            try:
                if item is _STOP:
                    return
                args, loss, t0 = item
                if self._error is not None:
                    continue  # keep draining so submit() can't deadlock
                try:
                    if self._profiler is not None:
                        jax.block_until_ready(loss)
                        self._profiler.record_step(time.perf_counter() - t0)
                    self._deliver(args, loss)
                except BaseException as e:
                    self._error = e
            finally:
                self._queue.task_done()

    def submit(self, args, loss) -> None:
        self.raise_pending()
        self._queue.put((args, loss, time.perf_counter()))

    def flush(self) -> None:
        """Barrier: every submitted delivery has run (epoch boundaries —
        ``on_epoch_end`` must observe all of its epoch's iterations)."""
        self._queue.join()
        self.raise_pending()

    def raise_pending(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def shutdown(self) -> None:
        """Drain remaining deliveries and stop the worker; never raises
        (exceptional exits must not mask the original error — the happy
        path calls :meth:`raise_pending` afterwards). Idempotent."""
        if self._closed:
            return
        self._closed = True
        self._queue.put(_STOP)
        self._thread.join()
