"""Checkpointing.

Rebuild of upstream ``org.deeplearning4j.optimize.listeners.CheckpointListener``
(periodic save every N iterations/epochs/minutes with keep-last-K retention)
plus a TPU-native addition the reference lacks: async, sharded checkpoints via
orbax (``OrbaxCheckpointer``) so multi-host state saves without stalling the
device. ``ModelSerializer`` zips remain the portable interchange format;
orbax is the training-loop format (SURVEY.md §5.4).

Crash safety (ISSUE 2): the reference's listener wrote archives in place —
a crash mid-``model.save`` left a truncated zip that a restart would
happily "restore". Here every archive is **atomic** (written to a tmp file
in the same directory, fsynced, then ``os.replace``d into place, directory
fsynced) and recorded in a per-directory CRC32 **manifest**
(``checkpoint_manifest.json``, itself written atomically).
:meth:`CheckpointListener.last_checkpoint_in` verifies candidates newest-
first — manifest CRC/size, then zip structure — and falls back to the
newest *valid* checkpoint, logging what it skipped, instead of returning
the lexically-newest path blindly. ``keep_every``-skipped checkpoints are
decided BEFORE saving: an archive destined for immediate deletion is never
written at all (the seed saved then unlinked — wasted IO and a window
where the newest file on disk was one scheduled for removal).

Chaos injection points (``runtime.chaos``): ``train.checkpoint.write``
fires before each archive write (fail/latency/hang policies);
``train.checkpoint.bytes`` is the byte point for
:class:`~deeplearning4j_tpu.runtime.chaos.CorruptBytes` — the manifest CRC
is computed from the *intended* bytes, so an injected torn write or
bit-flip is exactly what restore-time verification catches.
"""

from __future__ import annotations

import json
import os
import time
import zipfile
import zlib
from typing import Dict, List, Optional

from deeplearning4j_tpu.runtime import chaos, journal
from deeplearning4j_tpu.train.listeners import TrainingListener, logger

MANIFEST_NAME = "checkpoint_manifest.json"


def _checkpoint_index(filename: str) -> Optional[int]:
    """``checkpoint_<idx>_<tag>.zip`` -> idx, else None (foreign files —
    including the manifest — never break directory scans)."""
    parts = filename.split("_")
    if (len(parts) >= 3 and parts[0] == "checkpoint"
            and filename.endswith(".zip") and parts[1].isdigit()):
        return int(parts[1])
    return None


def _fsync_dir(path: str) -> None:
    """fsync a directory so a rename within it survives power loss (no-op
    on platforms whose dirs can't be opened)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _crc32_file(path: str) -> Dict[str, int]:
    crc, size = 0, 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
            size += len(chunk)
    return {"crc32": crc & 0xFFFFFFFF, "size": size}


def atomic_save_model(model, path: str, save_updater: bool = True) -> Dict[str, int]:
    """Crash-safe archive write: tmp file in the same directory (same
    filesystem, so the final ``os.replace`` is atomic), fsync, replace,
    directory fsync. Returns ``{"crc32", "size"}`` of the bytes *intended*
    for disk — computed before the chaos byte point, so injected write
    corruption is detectable against the returned digest."""
    d, base = os.path.split(os.path.abspath(path))
    tmp = os.path.join(d, f".{base}.tmp")
    chaos.inject("train.checkpoint.write")
    try:
        model.save(tmp, save_updater=save_updater)
        entry = _crc32_file(tmp)
        if chaos.active():
            with open(tmp, "rb") as f:
                data = f.read()
            corrupted = chaos.transform_bytes("train.checkpoint.bytes", data)
            if corrupted is not data:
                with open(tmp, "wb") as f:
                    f.write(corrupted)
        with open(tmp, "rb") as f:
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass
        raise
    _fsync_dir(d)
    # the checkpoint joins the black box (ISSUE 15): a resume/restart
    # investigation sees exactly which archives existed when
    journal.emit("train.checkpoint", path=path, size=entry["size"])
    return entry


def load_manifest(dir: str) -> Dict[str, Dict[str, int]]:
    try:
        with open(os.path.join(dir, MANIFEST_NAME)) as f:
            m = json.load(f)
        return m if isinstance(m, dict) else {}
    except (OSError, ValueError):
        return {}


def write_manifest(dir: str, manifest: Dict[str, Dict[str, int]]) -> None:
    path = os.path.join(dir, MANIFEST_NAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(dir)


def verify_checkpoint(path: str,
                      entry: Optional[Dict[str, int]] = None) -> bool:
    """Is ``path`` a restorable archive? Checks the manifest entry's
    size + CRC32 when given (catches silent bit rot), then the zip's own
    structure and per-member CRCs (catches truncation with no manifest)."""
    try:
        if entry is not None:
            actual = _crc32_file(path)
            if (actual["size"] != entry.get("size")
                    or actual["crc32"] != entry.get("crc32")):
                return False
        if not zipfile.is_zipfile(path):
            return False
        with zipfile.ZipFile(path) as zf:
            return zf.testzip() is None
    except (OSError, zipfile.BadZipFile):
        return False


class CheckpointListener(TrainingListener):
    """Save the model periodically (reference semantics + retention).

    Usage::

        net.set_listeners(CheckpointListener(
            dir="checkpoints", every_n_iterations=500, keep_last=3))
    """

    def __init__(self, dir: str, every_n_iterations: Optional[int] = None,
                 every_n_epochs: Optional[int] = None,
                 every_n_minutes: Optional[float] = None,
                 keep_last: Optional[int] = None, keep_every: int = 1,
                 save_updater: bool = True, clock=None):
        if not (every_n_iterations or every_n_epochs or every_n_minutes):
            raise ValueError("Configure at least one of every_n_iterations / "
                             "every_n_epochs / every_n_minutes")
        self.dir = dir
        self.every_n_iterations = every_n_iterations
        self.every_n_epochs = every_n_epochs
        self.every_n_minutes = every_n_minutes
        self.keep_last = keep_last
        self.keep_every = max(1, int(keep_every))
        self.save_updater = save_updater
        # the supervisor disarms an abandoned (hung-then-revoked) worker's
        # listener so a straggler step cannot write stale archives into a
        # directory the restarted run is checkpointing into
        self.armed = True
        # injectable clock (ISSUE 14: no wall clock in trajectory-adjacent
        # modules — and the every_n_minutes cadence wants a monotonic
        # reading anyway, immune to NTP steps mid-training)
        self._clock = clock if clock is not None else time.monotonic
        self._last_time = self._clock()
        self._saved: List[str] = []
        os.makedirs(dir, exist_ok=True)
        # Resume the checkpoint counter past anything already on disk: a
        # fresh listener after a supervisor restart must not reuse index 0
        # — that would overwrite the oldest archive with the NEWEST state
        # while last_checkpoint_in's newest-by-counter ordering still
        # preferred the stale higher indices.
        indices = [i for i in map(_checkpoint_index, os.listdir(dir))
                   if i is not None]
        self._count = max(indices) + 1 if indices else 0

    def _save(self, model, tag: str) -> None:
        if not self.armed:
            return
        idx = self._count
        self._count += 1
        # keep_every is decided BEFORE saving: never write an archive
        # destined for immediate deletion (the kept set matches the old
        # save-then-unlink behaviour: every keep_every-th trigger)
        if (idx + 1) % self.keep_every != 0:
            return
        path = os.path.join(self.dir, f"checkpoint_{idx}_{tag}.zip")
        entry = atomic_save_model(model, path, save_updater=self.save_updater)
        manifest = load_manifest(self.dir)
        manifest[os.path.basename(path)] = entry
        self._saved.append(path)
        logger.info("Saved checkpoint: %s", path)
        if self.keep_last:
            while len(self._saved) > self.keep_last:
                old = self._saved.pop(0)
                manifest.pop(os.path.basename(old), None)
                if os.path.exists(old):
                    os.unlink(old)
        write_manifest(self.dir, manifest)

    def iteration_done(self, model, iteration, epoch, score):
        if self.every_n_iterations and iteration % self.every_n_iterations == 0:
            self._save(model, f"iter{iteration}")
        if self.every_n_minutes and (self._clock() - self._last_time) >= 60 * self.every_n_minutes:
            self._save(model, f"iter{iteration}")
            self._last_time = self._clock()

    def on_epoch_end(self, model, epoch):
        if self.every_n_epochs and (epoch + 1) % self.every_n_epochs == 0:
            self._save(model, f"epoch{epoch}")

    def last_checkpoint(self) -> Optional[str]:
        return self._saved[-1] if self._saved else None

    @staticmethod
    def last_checkpoint_in(dir: str) -> Optional[str]:
        """Newest *valid* checkpoint in ``dir``, or None.

        Candidates are ordered newest-first by checkpoint counter; each is
        verified (manifest CRC/size when recorded, zip structure always)
        and unreadable/corrupt archives are skipped with a warning instead
        of being handed to a restart that would restore garbage."""
        try:
            files = [f for f in os.listdir(dir)
                     if _checkpoint_index(f) is not None]
        except OSError:
            return None
        if not files:
            return None
        files.sort(key=_checkpoint_index, reverse=True)
        manifest = load_manifest(dir)
        for f in files:
            path = os.path.join(dir, f)
            if verify_checkpoint(path, manifest.get(f)):
                return path
            logger.warning(
                "Skipping unreadable/corrupt checkpoint %s (%s); falling "
                "back to the previous one", path,
                "manifest CRC/size mismatch or bad zip" if f in manifest
                else "bad zip, no manifest entry")
        logger.warning("No valid checkpoint found in %s (%d candidate(s) "
                       "all corrupt)", dir, len(files))
        return None


class OrbaxCheckpointer:
    """Async sharded checkpointing of the raw TrainState (TPU-native path;
    no reference equivalent — the analog of its role is ModelSerializer).

    Saves params/opt_state/model_state with their shardings preserved;
    ``restore(net)`` loads back into an initialised network.
    """

    def __init__(self, dir: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.dir = os.path.abspath(dir)
        self.mngr = ocp.CheckpointManager(
            self.dir, options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, enable_async_checkpointing=True))

    @staticmethod
    def _rng_payload(net):
        """Fixed-structure RNG stream position (a lazily-uninitialised key
        is materialised to its origin, PRNGKey(seed), so save and restore
        targets always share one structure)."""
        import jax
        import numpy as np
        rs = net.rng.get_state()
        key = (np.asarray(rs["key"], np.uint32) if rs["key"] is not None
               else np.asarray(jax.random.PRNGKey(rs["seed"])))
        return {"seed": np.asarray(rs["seed"], np.int64), "key": key}

    def save(self, net, step: Optional[int] = None) -> None:
        ts = net.train_state
        step = int(ts.step) if step is None else int(step)
        self.mngr.save(step, args=self._ocp.args.StandardSave({
            "params": ts.params, "opt_state": ts.opt_state,
            "model_state": ts.model_state, "step": ts.step,
            "iteration": net._iteration, "epoch": net._epoch,
            "rng": self._rng_payload(net),
        }))

    def restore(self, net, step: Optional[int] = None):
        import dataclasses
        import numpy as np
        if net.train_state is None:
            net.init()
        ts = net.train_state
        step = self.mngr.latest_step() if step is None else step
        target = {"params": ts.params, "opt_state": ts.opt_state,
                  "model_state": ts.model_state, "step": ts.step,
                  "iteration": 0, "epoch": 0,
                  "rng": self._rng_payload(net)}
        try:
            restored = self.mngr.restore(
                step, args=self._ocp.args.StandardRestore(target))
        except ValueError:
            # Checkpoints written before the RNG payload existed have no
            # "rng" entry, and StandardRestore refuses a target whose tree
            # structure differs from disk — retry without it (the restored
            # net then starts a fresh stream from its seed, the old
            # behavior, instead of failing to resume at all).
            target.pop("rng")
            restored = self.mngr.restore(
                step, args=self._ocp.args.StandardRestore(target))
        net.train_state = dataclasses.replace(
            ts, params=restored["params"], opt_state=restored["opt_state"],
            model_state=restored["model_state"], step=restored["step"])
        net._iteration = int(restored.get("iteration", 0))
        net._epoch = int(restored.get("epoch", 0))
        rng = restored.get("rng")
        if rng is not None:
            net.rng.set_state({"seed": int(np.asarray(rng["seed"])),
                               "key": np.asarray(rng["key"]).tolist()})
        return net

    def wait(self) -> None:
        self.mngr.wait_until_finished()

    def close(self) -> None:
        self.mngr.close()
