"""Checkpointing.

Rebuild of upstream ``org.deeplearning4j.optimize.listeners.CheckpointListener``
(periodic save every N iterations/epochs/minutes with keep-last-K retention)
plus a TPU-native addition the reference lacks: async, sharded checkpoints via
orbax (``OrbaxCheckpointer``) so multi-host state saves without stalling the
device. ``ModelSerializer`` zips remain the portable interchange format;
orbax is the training-loop format (SURVEY.md §5.4).
"""

from __future__ import annotations

import os
import time
from typing import List, Optional

from deeplearning4j_tpu.train.listeners import TrainingListener, logger


class CheckpointListener(TrainingListener):
    """Save the model periodically (reference semantics + retention).

    Usage::

        net.set_listeners(CheckpointListener(
            dir="checkpoints", every_n_iterations=500, keep_last=3))
    """

    def __init__(self, dir: str, every_n_iterations: Optional[int] = None,
                 every_n_epochs: Optional[int] = None,
                 every_n_minutes: Optional[float] = None,
                 keep_last: Optional[int] = None, keep_every: int = 1,
                 save_updater: bool = True):
        if not (every_n_iterations or every_n_epochs or every_n_minutes):
            raise ValueError("Configure at least one of every_n_iterations / "
                             "every_n_epochs / every_n_minutes")
        self.dir = dir
        self.every_n_iterations = every_n_iterations
        self.every_n_epochs = every_n_epochs
        self.every_n_minutes = every_n_minutes
        self.keep_last = keep_last
        self.keep_every = max(1, int(keep_every))
        self.save_updater = save_updater
        self._last_time = time.time()
        self._saved: List[str] = []
        self._count = 0
        os.makedirs(dir, exist_ok=True)

    def _save(self, model, tag: str) -> None:
        path = os.path.join(self.dir, f"checkpoint_{self._count}_{tag}.zip")
        model.save(path, save_updater=self.save_updater)
        self._count += 1
        if self._count % self.keep_every == 0:
            self._saved.append(path)
        else:
            os.unlink(path)
            return
        logger.info("Saved checkpoint: %s", path)
        if self.keep_last:
            while len(self._saved) > self.keep_last:
                old = self._saved.pop(0)
                if os.path.exists(old):
                    os.unlink(old)

    def iteration_done(self, model, iteration, epoch, score):
        if self.every_n_iterations and iteration % self.every_n_iterations == 0:
            self._save(model, f"iter{iteration}")
        if self.every_n_minutes and (time.time() - self._last_time) >= 60 * self.every_n_minutes:
            self._save(model, f"iter{iteration}")
            self._last_time = time.time()

    def on_epoch_end(self, model, epoch):
        if self.every_n_epochs and (epoch + 1) % self.every_n_epochs == 0:
            self._save(model, f"epoch{epoch}")

    def last_checkpoint(self) -> Optional[str]:
        return self._saved[-1] if self._saved else None

    @staticmethod
    def last_checkpoint_in(dir: str) -> Optional[str]:
        files = [f for f in os.listdir(dir)
                 if f.startswith("checkpoint_") and f.endswith(".zip")]
        if not files:
            return None
        files.sort(key=lambda f: int(f.split("_")[1]))
        return os.path.join(dir, files[-1])


class OrbaxCheckpointer:
    """Async sharded checkpointing of the raw TrainState (TPU-native path;
    no reference equivalent — the analog of its role is ModelSerializer).

    Saves params/opt_state/model_state with their shardings preserved;
    ``restore(net)`` loads back into an initialised network.
    """

    def __init__(self, dir: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.dir = os.path.abspath(dir)
        self.mngr = ocp.CheckpointManager(
            self.dir, options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, enable_async_checkpointing=True))

    @staticmethod
    def _rng_payload(net):
        """Fixed-structure RNG stream position (a lazily-uninitialised key
        is materialised to its origin, PRNGKey(seed), so save and restore
        targets always share one structure)."""
        import jax
        import numpy as np
        rs = net.rng.get_state()
        key = (np.asarray(rs["key"], np.uint32) if rs["key"] is not None
               else np.asarray(jax.random.PRNGKey(rs["seed"])))
        return {"seed": np.asarray(rs["seed"], np.int64), "key": key}

    def save(self, net, step: Optional[int] = None) -> None:
        ts = net.train_state
        step = int(ts.step) if step is None else int(step)
        self.mngr.save(step, args=self._ocp.args.StandardSave({
            "params": ts.params, "opt_state": ts.opt_state,
            "model_state": ts.model_state, "step": ts.step,
            "iteration": net._iteration, "epoch": net._epoch,
            "rng": self._rng_payload(net),
        }))

    def restore(self, net, step: Optional[int] = None):
        import dataclasses
        import numpy as np
        if net.train_state is None:
            net.init()
        ts = net.train_state
        step = self.mngr.latest_step() if step is None else step
        target = {"params": ts.params, "opt_state": ts.opt_state,
                  "model_state": ts.model_state, "step": ts.step,
                  "iteration": 0, "epoch": 0,
                  "rng": self._rng_payload(net)}
        try:
            restored = self.mngr.restore(
                step, args=self._ocp.args.StandardRestore(target))
        except ValueError:
            # Checkpoints written before the RNG payload existed have no
            # "rng" entry, and StandardRestore refuses a target whose tree
            # structure differs from disk — retry without it (the restored
            # net then starts a fresh stream from its seed, the old
            # behavior, instead of failing to resume at all).
            target.pop("rng")
            restored = self.mngr.restore(
                step, args=self._ocp.args.StandardRestore(target))
        net.train_state = dataclasses.replace(
            ts, params=restored["params"], opt_state=restored["opt_state"],
            model_state=restored["model_state"], step=restored["step"])
        net._iteration = int(restored.get("iteration", 0))
        net._epoch = int(restored.get("epoch", 0))
        rng = restored.get("rng")
        if rng is not None:
            net.rng.set_state({"seed": int(np.asarray(rng["seed"])),
                               "key": np.asarray(rng["key"]).tolist()})
        return net

    def wait(self) -> None:
        self.mngr.wait_until_finished()

    def close(self) -> None:
        self.mngr.close()
