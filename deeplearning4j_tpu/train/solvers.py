"""Legacy second-order / line-search solvers (reference
``org.deeplearning4j.optimize.solvers``: ``LBFGS``, ``ConjugateGradient``,
``LineGradientDescent`` beside the default ``StochasticGradientDescent``).

TPU-first shape: the whole per-batch inner optimization (K solver iterations,
each with value/grad + zoom line search) compiles to ONE program — a
``lax.scan`` over jitted iterations (the reference runs the same structure
through ``Solver.optimize`` with per-op dispatch). The compiled program is
cached on the network like the SGD train step, so repeated batches do not
retrace.

- LBFGS: ``optax.lbfgs`` (memory-10).
- CONJUGATE_GRADIENT: Polak-Ribiere+ nonlinear CG with restart.
- LINE_GRADIENT_DESCENT: steepest descent.

All three cap their zoom line search at the builder's
``maxNumLineSearchIterations`` (reference semantics: the line-search step
budget); the outer per-batch iteration count is ``solver_iterations``.
Frozen layers stay frozen (their gradient subtrees are zeroed before the
solver update — the SGD path freezes via per-label optax.set_to_zero
instead), and the final forward's model state (BatchNorm running stats
etc.) is kept.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax


class _CGState(NamedTuple):
    prev_grad: Any
    direction: Any
    linesearch: Any


def conjugate_gradient(max_linesearch_steps: int = 15):
    """Polak-Ribiere+ nonlinear conjugate gradient as an optax
    GradientTransformationExtraArgs (needs value/grad/value_fn like lbfgs)."""
    ls = optax.scale_by_zoom_linesearch(max_linesearch_steps=max_linesearch_steps)

    def init_fn(params):
        zeros = jax.tree.map(jnp.zeros_like, params)
        return _CGState(prev_grad=zeros, direction=zeros,
                        linesearch=ls.init(params))

    def update_fn(grads, state, params=None, *, value, grad, value_fn, **kw):
        g_dot = sum(jnp.vdot(a, a) for a in jax.tree_util.tree_leaves(state.prev_grad))
        gg = sum(jnp.vdot(g, g - pg) for g, pg in zip(
            jax.tree_util.tree_leaves(grads),
            jax.tree_util.tree_leaves(state.prev_grad)))
        beta = jnp.where(g_dot > 0, jnp.maximum(gg / jnp.maximum(g_dot, 1e-30), 0.0), 0.0)
        direction = jax.tree.map(lambda g, d: -g + beta * d, grads, state.direction)
        # zoom line search expects a DESCENT direction as the updates and
        # scales it by the accepted step size
        updates, ls_state = ls.update(
            direction, state.linesearch, params,
            value=value, grad=grad, value_fn=value_fn)
        new_state = _CGState(prev_grad=grads, direction=direction,
                             linesearch=ls_state)
        return updates, new_state

    return optax.GradientTransformationExtraArgs(init_fn, update_fn)


def line_gradient_descent(max_linesearch_steps: int = 15):
    """Steepest descent with zoom line search (reference
    ``LineGradientDescent``): negate the gradient, then scale by the accepted
    step size."""
    return optax.chain(
        optax.scale(-1.0),
        optax.scale_by_zoom_linesearch(
            max_linesearch_steps=max_linesearch_steps))


def make_solver(algo: str, max_linesearch_steps: int = 15):
    algo = algo.upper()
    if algo == "LBFGS":
        return optax.lbfgs(linesearch=optax.scale_by_zoom_linesearch(
            max_linesearch_steps=max_linesearch_steps))
    if algo == "CONJUGATE_GRADIENT":
        return conjugate_gradient(max_linesearch_steps)
    if algo == "LINE_GRADIENT_DESCENT":
        return line_gradient_descent(max_linesearch_steps)
    raise ValueError(f"unknown optimization algorithm {algo!r}")


def _solver_core(net, frozen_keys, loss_fn, cache_suffix, args):
    """Shared K-iteration solver inner loop (MultiLayerNetwork and
    ComputationGraph differ only in their loss signature). ``loss_fn`` is
    ``(params, model_state, rng, *args) -> (loss, new_model_state)``.

    Dropout note: one rng is drawn PER BATCH and reused across the inner
    iterations — the zoom line search needs a deterministic value_fn, so the
    dropout mask is frozen for the batch (the reference's Solver holds one
    dropout mask per optimize() call the same way)."""
    g = net.conf.global_conf
    algo = g.optimization_algo
    max_ls = max(1, int(g.max_num_line_search_iterations))
    iters = max(1, int(getattr(g, "solver_iterations", 10)))
    tx = make_solver(algo, max_ls)

    def make():
        def run(params, model_state, rng, args):
            def value_fn(p):
                loss, _ = loss_fn(p, model_state, rng, *args)
                return loss

            def mask_frozen(grads):
                return {k: (jax.tree.map(jnp.zeros_like, v)
                            if k in frozen_keys else v)
                        for k, v in grads.items()}

            def body(carry, _):
                params, opt_state = carry
                value, grads = jax.value_and_grad(value_fn)(params)
                grads = mask_frozen(grads)  # frozen layers stay frozen
                updates, opt_state = tx.update(
                    grads, opt_state, params, value=value, grad=grads,
                    value_fn=value_fn)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), value

            (params, _), _ = jax.lax.scan(body, (params, tx.init(params)),
                                          None, length=iters)
            # final forward keeps the training-mode model state (BN stats)
            loss, new_state = loss_fn(params, model_state, rng, *args)
            return params, new_state, loss
        return jax.jit(run)

    run = net._jitted(f"solver_{algo}_{iters}_{max_ls}_{cache_suffix}", make)
    ts = net.train_state
    rng = net.rng.next_key()
    new_params, new_state, loss = run(ts.params, ts.model_state, rng, args)
    import dataclasses as _dc
    net.train_state = _dc.replace(ts, params=new_params,
                                  model_state=new_state, step=ts.step + 1)
    return float(loss)


def solver_fit_batch(net, x, y, fmask=None, lmask=None):
    """One reference-``Solver.optimize`` pass on this batch
    (MultiLayerNetwork). Params AND model state are updated in the network's
    train state; returns the final loss."""
    from deeplearning4j_tpu.models.multi_layer_network import _layer_key
    frozen_keys = {_layer_key(i, layer)
                   for i, layer in enumerate(net.layers)
                   if getattr(layer, "frozen", False)}

    def loss_fn(p, model_state, rng, x, y, fmask, lmask):
        loss, (new_state, _) = net._loss(p, model_state, x, y, rng,
                                         fmask, lmask, training=True)
        return loss, new_state

    return _solver_core(net, frozen_keys, loss_fn, "mln",
                        (x, y, fmask, lmask))


def graph_solver_fit_batch(net, inputs, labels, masks=None):
    """ComputationGraph variant of :func:`solver_fit_batch`."""
    frozen_keys = {n.name for n in net.conf.nodes
                   if n.kind == "layer" and getattr(n.obj, "frozen", False)}

    def loss_fn(p, model_state, rng, inputs, labels, masks):
        loss, (new_state, _) = net._loss(p, model_state, inputs, labels,
                                         rng, masks)
        return loss, new_state

    return _solver_core(net, frozen_keys, loss_fn, "graph",
                        (inputs, labels, masks))
