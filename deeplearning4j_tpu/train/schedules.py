"""Learning-rate schedules.

Rebuild of upstream ``org.nd4j.linalg.schedule.*`` (``StepSchedule``,
``ExponentialSchedule``, ``InverseSchedule``, ``PolySchedule``,
``SigmoidSchedule``, ``MapSchedule``, ``CycleSchedule``). A schedule is a
dataclass with ``value_at(step)`` usable directly as an optax schedule
(callable on a jnp step counter inside jit).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Type

import jax.numpy as jnp

_SCHED_REGISTRY: Dict[str, Type["Schedule"]] = {}


def register_schedule(cls):
    _SCHED_REGISTRY[cls.__name__] = cls
    return cls


@dataclasses.dataclass
class Schedule:
    initial_value: float = 1e-3

    def value_at(self, step):
        return jnp.asarray(self.initial_value, jnp.float32)

    def __call__(self, step):
        return self.value_at(step)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["@type"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d: dict) -> "Schedule":
        d = dict(d)
        cls = _SCHED_REGISTRY[d.pop("@type")]
        if cls is MapSchedule and "values" in d:
            d["values"] = {int(k): float(v) for k, v in d["values"].items()}
        return cls(**d)


@register_schedule
@dataclasses.dataclass
class StepSchedule(Schedule):
    """value * decay_rate ^ floor(step / step_size)"""

    decay_rate: float = 0.1
    step_size: int = 1000

    def value_at(self, step):
        return self.initial_value * self.decay_rate ** jnp.floor(step / self.step_size)


@register_schedule
@dataclasses.dataclass
class ExponentialSchedule(Schedule):
    gamma: float = 0.99

    def value_at(self, step):
        return self.initial_value * self.gamma ** jnp.asarray(step, jnp.float32)


@register_schedule
@dataclasses.dataclass
class InverseSchedule(Schedule):
    gamma: float = 0.01
    power: float = 1.0

    def value_at(self, step):
        return self.initial_value / (1.0 + self.gamma * step) ** self.power


@register_schedule
@dataclasses.dataclass
class PolySchedule(Schedule):
    power: float = 2.0
    max_iter: int = 10000

    def value_at(self, step):
        frac = jnp.clip(step / self.max_iter, 0.0, 1.0)
        return self.initial_value * (1.0 - frac) ** self.power


@register_schedule
@dataclasses.dataclass
class SigmoidSchedule(Schedule):
    gamma: float = 0.01
    step_size: int = 1000

    def value_at(self, step):
        return self.initial_value / (1.0 + jnp.exp(self.gamma * (step - self.step_size)))


@register_schedule
@dataclasses.dataclass
class MapSchedule(Schedule):
    """Piecewise-constant: {step: value}, holds last value."""

    values: Dict[int, float] = dataclasses.field(default_factory=dict)

    def value_at(self, step):
        keys = sorted(self.values)
        out = jnp.asarray(self.initial_value, jnp.float32)
        for k in keys:
            out = jnp.where(step >= k, self.values[k], out)
        return out


@register_schedule
@dataclasses.dataclass
class CycleSchedule(Schedule):
    """1cycle policy (reference ``CycleSchedule``): ramp up, ramp down, then
    annihilate over the final fraction."""

    max_value: float = 1e-2
    cycle_length: int = 1000
    annealing_length: int = 100
    annealing_decay: float = 0.1

    def value_at(self, step):
        up = self.cycle_length // 2
        pos = jnp.mod(step, self.cycle_length + self.annealing_length)
        ramp_up = self.initial_value + (self.max_value - self.initial_value) * (pos / jnp.maximum(up, 1))
        ramp_down = self.max_value - (self.max_value - self.initial_value) * ((pos - up) / jnp.maximum(up, 1))
        anneal = self.initial_value * (
            1.0 - (1.0 - self.annealing_decay) *
            jnp.clip((pos - self.cycle_length) / jnp.maximum(self.annealing_length, 1), 0.0, 1.0))
        v = jnp.where(pos < up, ramp_up, jnp.where(pos < self.cycle_length, ramp_down, anneal))
        return v
