"""Failure detection / auto-resume (SURVEY.md §5.3).

The reference's fault story is: workers heartbeat into the parameter-server
mesh (upstream ``org.nd4j.parameterserver.distributed.v2.util.MeshOrganizer``
join/leave remap) and training restarts from the last checkpoint. On TPU the
SPMD program is all-or-nothing — a lost chip kills the step — so the
TPU-native equivalent is supervision AROUND the compiled step:
checkpoint periodically, detect the failure (exception or watchdog timeout),
restore the newest checkpoint, and continue the epoch loop.

``FaultTolerantTrainer`` is that supervisor for single-controller training;
on multihost each controller runs the same loop and
``runtime.mesh.initialize_multihost`` re-forms the mesh on restart.

ISSUE 2 upgrades (chaos-hardened in ``tests/test_chaos.py``):

- **Real supervision**: with a heartbeat timeout configured, each epoch
  runs in a worker thread while the supervisor polls the
  :class:`HeartbeatMonitor` — a *hung* step (not just a raised one) is
  detected, the stalled worker is abandoned (on real hardware the chip
  behind it is gone), and training restarts from the newest valid
  checkpoint.
- **Bounded restart budget**: ``max_restarts`` within
  ``restart_window_s`` (lifetime when None). When the budget is
  exhausted the supervisor stops retrying and escalates
  :class:`TrainingFailure` — a crash loop must page a human, not burn
  accelerator time forever.
- **Exact mid-epoch resume**: the trainer records the iteration at which
  each epoch began; after restoring a checkpoint taken mid-epoch it skips
  the already-trained leading batches of that epoch, so the resumed loss
  trajectory bit-matches an uninterrupted run (the serializer already
  restores updater state, iteration/epoch counters, and the RNG stream
  position).
- **Corruption-aware restore**: ``CheckpointListener.last_checkpoint_in``
  now verifies archives (CRC manifest + zip structure) and falls back to
  the newest *valid* checkpoint, so a crash mid-save can no longer feed a
  truncated zip to the restart.

Chaos injection point: ``train.epoch`` fires inside the epoch worker just
before ``net.fit`` (fail → supervised restart; hang → watchdog abandon).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from typing import Callable, Optional

from deeplearning4j_tpu.runtime import chaos, journal
from deeplearning4j_tpu.train.checkpoint import CheckpointListener

logger = logging.getLogger(__name__)


class TrainingFailure(RuntimeError):
    pass


class HeartbeatMonitor:
    """Liveness watchdog (the heartbeat half of the reference's mesh
    organizer): training calls :meth:`beat` every iteration; a supervisor
    thread — or the trainer itself between epochs — calls :meth:`check`
    and treats a stale heartbeat as a failure."""

    def __init__(self, timeout_s: float = 600.0):
        self.timeout_s = float(timeout_s)
        self._last = time.monotonic()

    def beat(self) -> None:
        self._last = time.monotonic()

    def seconds_since_beat(self) -> float:
        return time.monotonic() - self._last

    def check(self) -> None:
        if self.seconds_since_beat() > self.timeout_s:
            raise TrainingFailure(
                f"no training heartbeat for {self.seconds_since_beat():.0f}s "
                f"(timeout {self.timeout_s:.0f}s)")


class _HeartbeatListener:
    """TrainingListener shim feeding the monitor."""

    def __init__(self, monitor: HeartbeatMonitor):
        self.monitor = monitor

    def iteration_done(self, model, iteration, epoch, score):
        self.monitor.beat()

    def on_epoch_start(self, model, epoch):
        pass

    def on_epoch_end(self, model, epoch):
        pass


class _FencedIterator:
    """Iterator wrapper the supervisor can revoke: an abandoned (hung)
    epoch worker that later wakes up sees an exhausted iterator instead of
    racing the restarted epoch for batches."""

    def __init__(self, base):
        self.base = base
        self._revoked = False

    def revoke(self) -> None:
        self._revoked = True

    def reset(self) -> None:
        if not self._revoked:
            self.base.reset()

    def has_next(self) -> bool:
        return (not self._revoked) and self.base.has_next()

    def next(self):
        if self._revoked:
            raise StopIteration("iterator revoked by the supervisor")
        return self.base.next()

    def batch(self) -> int:
        return self.base.batch()

    def set_pre_processor(self, p) -> None:
        self.base.set_pre_processor(p)

    def __iter__(self):
        while self.has_next():
            yield self.next()


class _SkipBatches:
    """Iterator wrapper that discards the first ``skip`` batches after each
    reset — the mid-epoch resume mechanism: a deterministic iterator
    replays the epoch's prefix into the void so training continues at
    exactly the batch the checkpoint was taken after."""

    def __init__(self, base, skip: int):
        self.base = base
        self.skip = int(skip)

    def reset(self) -> None:
        self.base.reset()
        for _ in range(self.skip):
            if not self.base.has_next():
                break
            self.base.next()

    def has_next(self) -> bool:
        return self.base.has_next()

    def next(self):
        return self.base.next()

    def batch(self) -> int:
        return self.base.batch()

    def set_pre_processor(self, p) -> None:
        self.base.set_pre_processor(p)

    def __iter__(self):
        while self.has_next():
            yield self.next()


class FaultTolerantTrainer:
    """Checkpoint + restart supervision loop.

    ``make_net()`` must build a FRESH, initialised network (the replacement
    worker). ``fit`` runs epoch-at-a-time; on any failure — a raised
    exception, or a stale heartbeat when ``heartbeat_timeout_s`` is set —
    it reloads the newest *valid* checkpoint from ``checkpoint_dir`` into
    a fresh network and continues, within the restart budget
    (``max_restarts`` per ``restart_window_s``; lifetime when the window
    is None). An exhausted budget escalates :class:`TrainingFailure`.
    """

    def __init__(self, make_net: Callable[[], object], checkpoint_dir: str,
                 every_n_iterations: int = 50, keep_last: int = 3,
                 max_restarts: int = 3,
                 restart_window_s: Optional[float] = None,
                 heartbeat_timeout_s: Optional[float] = None):
        self.make_net = make_net
        self.checkpoint_dir = checkpoint_dir
        self.every_n_iterations = every_n_iterations
        self.keep_last = keep_last
        self.max_restarts = max_restarts
        self.restart_window_s = restart_window_s
        self.restarts = 0
        self.monitor = (HeartbeatMonitor(heartbeat_timeout_s)
                        if heartbeat_timeout_s else None)
        self._restart_times: deque = deque()
        os.makedirs(checkpoint_dir, exist_ok=True)
        # epoch -> iteration at which it began; persisted next to the
        # checkpoints so a BRAND-NEW trainer over an existing directory
        # (cross-process restart) still resumes mid-epoch exactly instead
        # of replaying the epoch's leading batches
        self._epoch_start_iters = self._load_epoch_starts()

    def _epoch_starts_path(self) -> str:
        return os.path.join(self.checkpoint_dir, "trainer_state.json")

    def _load_epoch_starts(self) -> dict:
        import json
        try:
            with open(self._epoch_starts_path()) as f:
                return {int(k): int(v) for k, v in
                        json.load(f)["epoch_start_iters"].items()}
        except (OSError, ValueError, KeyError, TypeError):
            return {}

    def _save_epoch_starts(self) -> None:
        import json
        path = self._epoch_starts_path()
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump({"epoch_start_iters": self._epoch_start_iters}, f)
            os.replace(tmp, path)
        except OSError:
            logger.warning("could not persist trainer state to %s", path)

    def _fresh_net(self):
        base = self.make_net()  # one build: class, listeners, or the net itself
        listeners = list(getattr(base, "_listeners", []))
        ckpt = CheckpointListener.last_checkpoint_in(self.checkpoint_dir)
        if ckpt is not None:
            logger.warning("Restoring from checkpoint %s", ckpt)
            net = type(base).load(ckpt)
            # resume on the black-box record (ISSUE 15): which archive a
            # restarted trainer actually picked up
            journal.emit("train.resume", checkpoint=ckpt)
        else:
            net = base
        listeners.append(CheckpointListener(
            self.checkpoint_dir, every_n_iterations=self.every_n_iterations,
            keep_last=self.keep_last))
        if self.monitor:
            listeners.append(_HeartbeatListener(self.monitor))
        net.set_listeners(*listeners)
        return net

    # ----------------------------------------------------------- internals
    def _run_epoch(self, net, iterator) -> Optional[BaseException]:
        """Run ONE epoch; returns None on success or the failure cause.
        With a heartbeat monitor the epoch runs in a worker thread and the
        supervisor polls for staleness — a hung worker is abandoned (its
        eventual result, if any, is ignored) and reported as a failure."""
        box = {}

        def work():
            try:
                chaos.inject("train.epoch")
                net.fit(iterator, epochs=1)
            except BaseException as e:  # noqa: BLE001 — any failure counts
                box["err"] = e

        if self.monitor is None:
            work()
            return box.get("err")
        self.monitor.beat()  # epoch start counts as liveness
        worker = threading.Thread(target=work, daemon=True,
                                  name="FaultTolerantTrainer-epoch")
        worker.start()
        poll = max(0.01, min(0.5, self.monitor.timeout_s / 4.0))
        while worker.is_alive():
            worker.join(poll)
            if not worker.is_alive():
                break
            if self.monitor.seconds_since_beat() > self.monitor.timeout_s:
                # Quarantine the stalled worker before abandoning it: if
                # it ever wakes up it must not race the restarted epoch —
                # its iterator is revoked (no more batches) and its
                # checkpoint listeners are disarmed (no stale archives
                # into the directory the new attempt checkpoints into).
                if isinstance(iterator, _FencedIterator):
                    iterator.revoke()
                for lst in getattr(net, "_listeners", []):
                    if isinstance(lst, CheckpointListener):
                        lst.armed = False
                return TrainingFailure(
                    f"no training heartbeat for "
                    f"{self.monitor.seconds_since_beat():.1f}s (timeout "
                    f"{self.monitor.timeout_s:.1f}s); abandoning the "
                    f"stalled epoch worker")
        return box.get("err")

    def _register_restart(self, cause: BaseException) -> None:
        """Count a restart against the budget; escalate when exhausted."""
        now = time.monotonic()
        self.restarts += 1
        self._restart_times.append(now)
        if self.restart_window_s is not None:
            while (self._restart_times
                   and now - self._restart_times[0] > self.restart_window_s):
                self._restart_times.popleft()
            recent = len(self._restart_times)
            budget = (f"{self.max_restarts} restarts in "
                      f"{self.restart_window_s:.0f}s")
        else:
            recent = self.restarts
            budget = f"{self.max_restarts} restarts"
        if recent > self.max_restarts:
            raise TrainingFailure(f"giving up after {budget}") from cause
        journal.emit("train.restart", cause=type(cause).__name__,
                     restarts=self.restarts)
        logger.warning("Training failed (%s); restart %d within budget %s",
                       cause, recent, budget)

    # ----------------------------------------------------------------- fit
    def fit(self, iterator, epochs: int = 1):
        """Supervised training; returns the final (possibly restarted) net.

        Epoch progress is tracked on the NET's epoch counter (restored
        from checkpoints), so a restart resumes at the checkpoint's epoch
        — and, via batch skipping, at the checkpoint's exact batch."""
        net = self._fresh_net()
        while net._epoch < epochs:
            e = net._epoch
            start_iter = self._epoch_start_iters.get(e)
            if start_iter is None:
                self._epoch_start_iters[e] = net._iteration
                self._save_epoch_starts()
                skip = 0
            else:
                # resumed mid-epoch: the checkpoint's iteration counter
                # minus the recorded epoch start = batches already trained
                skip = max(0, net._iteration - start_iter)
            it = _SkipBatches(iterator, skip) if skip else iterator
            if self.monitor is not None:
                it = _FencedIterator(it)  # revocable on watchdog abandon
            failure = self._run_epoch(net, it)
            if failure is None:
                continue  # net.fit advanced net._epoch
            self._register_restart(failure)
            net = self._fresh_net()
        return net
