"""Failure detection / auto-resume (SURVEY.md §5.3).

The reference's fault story is: workers heartbeat into the parameter-server
mesh (upstream ``org.nd4j.parameterserver.distributed.v2.util.MeshOrganizer``
join/leave remap) and training restarts from the last checkpoint. On TPU the
SPMD program is all-or-nothing — a lost chip kills the step — so the
TPU-native equivalent is supervision AROUND the compiled step:
checkpoint periodically, detect the failure (exception or watchdog timeout),
restore the newest checkpoint, and continue the epoch loop.

``FaultTolerantTrainer`` is that supervisor for single-controller training;
on multihost each controller runs the same loop and
``runtime.mesh.initialize_multihost`` re-forms the mesh on restart.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Callable, Optional

from deeplearning4j_tpu.train.checkpoint import CheckpointListener

logger = logging.getLogger(__name__)


class TrainingFailure(RuntimeError):
    pass


class HeartbeatMonitor:
    """Liveness watchdog (the heartbeat half of the reference's mesh
    organizer): training calls :meth:`beat` every iteration; a supervisor
    thread — or the trainer itself between epochs — calls :meth:`check`
    and treats a stale heartbeat as a failure."""

    def __init__(self, timeout_s: float = 600.0):
        self.timeout_s = float(timeout_s)
        self._last = time.monotonic()

    def beat(self) -> None:
        self._last = time.monotonic()

    def seconds_since_beat(self) -> float:
        return time.monotonic() - self._last

    def check(self) -> None:
        if self.seconds_since_beat() > self.timeout_s:
            raise TrainingFailure(
                f"no training heartbeat for {self.seconds_since_beat():.0f}s "
                f"(timeout {self.timeout_s:.0f}s)")


class _HeartbeatListener:
    """TrainingListener shim feeding the monitor."""

    def __init__(self, monitor: HeartbeatMonitor):
        self.monitor = monitor

    def iteration_done(self, model, iteration, epoch, score):
        self.monitor.beat()

    def on_epoch_start(self, model, epoch):
        pass

    def on_epoch_end(self, model, epoch):
        pass


class FaultTolerantTrainer:
    """Checkpoint + restart supervision loop.

    ``make_net()`` must build a FRESH, initialised network (the replacement
    worker). ``fit`` runs epoch-at-a-time; on any exception it reloads the
    newest checkpoint from ``checkpoint_dir`` into a fresh network and
    continues, up to ``max_restarts`` times.
    """

    def __init__(self, make_net: Callable[[], object], checkpoint_dir: str,
                 every_n_iterations: int = 50, keep_last: int = 3,
                 max_restarts: int = 3,
                 heartbeat_timeout_s: Optional[float] = None):
        self.make_net = make_net
        self.checkpoint_dir = checkpoint_dir
        self.every_n_iterations = every_n_iterations
        self.keep_last = keep_last
        self.max_restarts = max_restarts
        self.restarts = 0
        self.monitor = (HeartbeatMonitor(heartbeat_timeout_s)
                        if heartbeat_timeout_s else None)
        os.makedirs(checkpoint_dir, exist_ok=True)

    def _fresh_net(self):
        base = self.make_net()  # one build: class, listeners, or the net itself
        listeners = list(getattr(base, "_listeners", []))
        ckpt = CheckpointListener.last_checkpoint_in(self.checkpoint_dir)
        if ckpt is not None:
            logger.warning("Restoring from checkpoint %s", ckpt)
            net = type(base).load(ckpt)
        else:
            net = base
        listeners.append(CheckpointListener(
            self.checkpoint_dir, every_n_iterations=self.every_n_iterations,
            keep_last=self.keep_last))
        if self.monitor:
            listeners.append(_HeartbeatListener(self.monitor))
        net.set_listeners(*listeners)
        return net

    def fit(self, iterator, epochs: int = 1):
        """Supervised training; returns the final (possibly restarted) net."""
        net = self._fresh_net()
        epoch = 0
        while epoch < epochs:
            try:
                net.fit(iterator, epochs=1)
                if self.monitor:
                    self.monitor.check()
                epoch += 1
            except Exception as e:  # noqa: BLE001 — any failure -> restart
                self.restarts += 1
                if self.restarts > self.max_restarts:
                    raise TrainingFailure(
                        f"giving up after {self.max_restarts} restarts") from e
                logger.warning("Training failed (%s); restart %d/%d",
                               e, self.restarts, self.max_restarts)
                net = self._fresh_net()
        return net
