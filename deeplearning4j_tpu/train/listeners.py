"""Training listener SPI.

Rebuild of upstream ``org.deeplearning4j.optimize.api.TrainingListener`` and
the stock listeners (``ScoreIterationListener``, ``PerformanceListener``,
``EvaluativeListener``). Listeners run on the host between jitted steps; to
keep the device busy, score values arrive as (possibly not-yet-ready) jax
arrays and are only synced when a listener actually reads them.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Optional

logger = logging.getLogger("deeplearning4j_tpu")


class TrainingListener:
    """SPI — subclass and override what you need (reference interface)."""

    #: Whether this listener reads ``model.train_state`` (params/activations)
    #: in its callbacks. Listeners that only consume the score/batch counters
    #: override this to False, which lets ``fit`` keep the training state in
    #: its packed flat-buffer form between steps (see
    #: :mod:`deeplearning4j_tpu.runtime.state_packing`).
    needs_model_state = True

    def iteration_done(self, model, iteration: int, epoch: int, score) -> None:
        pass

    def on_epoch_start(self, model, epoch: int) -> None:
        pass

    def on_epoch_end(self, model, epoch: int) -> None:
        pass

    def on_forward_pass(self, model, activations=None) -> None:
        pass

    def on_backward_pass(self, model) -> None:
        pass

    def on_gradient_calculation(self, model) -> None:
        pass


BaseTrainingListener = TrainingListener  # reference has an adapter base class


class ScoreIterationListener(TrainingListener):
    """Log score every N iterations (reference ``ScoreIterationListener``)."""

    needs_model_state = False

    def __init__(self, print_iterations: int = 10):
        self.print_iterations = max(1, int(print_iterations))

    def iteration_done(self, model, iteration, epoch, score):
        if iteration % self.print_iterations == 0:
            logger.info("Score at iteration %d (epoch %d) is %s", iteration, epoch, float(score))


class PerformanceListener(TrainingListener):
    """Throughput reporting (reference ``PerformanceListener``): batches/sec,
    samples/sec, ETL fraction."""

    needs_model_state = False

    def __init__(self, frequency: int = 10, report_samples: bool = True):
        self.frequency = max(1, int(frequency))
        self.report_samples = report_samples
        self._last_time: Optional[float] = None
        self._last_iter = 0
        self._samples = 0

    def record_batch(self, n_examples: int) -> None:
        self._samples += int(n_examples)

    def iteration_done(self, model, iteration, epoch, score):
        now = time.perf_counter()
        if self._last_time is None:
            self._last_time, self._last_iter, self._samples = now, iteration, 0
            return
        if iteration - self._last_iter >= self.frequency:
            dt = now - self._last_time
            it_s = (iteration - self._last_iter) / dt
            msg = f"iteration {iteration} (epoch {epoch}): {it_s:.1f} it/s"
            if self.report_samples and self._samples:
                msg += f", {self._samples / dt:.1f} samples/s"
            msg += f", score={float(score):.5f}"
            logger.info(msg)
            self._last_time, self._last_iter, self._samples = now, iteration, 0


class EvaluativeListener(TrainingListener):
    """Periodically evaluate on a held-out iterator (reference
    ``EvaluativeListener``)."""

    def __init__(self, iterator, frequency: int = 100, evaluation_factory=None):
        self.iterator = iterator
        self.frequency = max(1, int(frequency))
        self.evaluation_factory = evaluation_factory
        self.last_evaluation = None

    def iteration_done(self, model, iteration, epoch, score):
        if iteration > 0 and iteration % self.frequency == 0:
            self.iterator.reset()
            self.last_evaluation = model.evaluate(self.iterator)
            logger.info("Evaluation at iteration %d:\n%s", iteration, self.last_evaluation.stats())


class CollectScoresListener(TrainingListener):
    """Collect (iteration, score) pairs in memory (reference
    ``CollectScoresIterationListener``) — used by tests and loss-curve goldens."""

    needs_model_state = False

    def __init__(self):
        self.scores: list[tuple[int, float]] = []

    def iteration_done(self, model, iteration, epoch, score):
        self.scores.append((iteration, float(score)))
