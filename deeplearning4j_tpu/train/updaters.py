"""Updaters (optimizers).

Rebuild of upstream ``org.nd4j.linalg.learning.config.*`` — Sgd, Adam, AdaMax,
AMSGrad, Nadam, Nesterovs, RmsProp, AdaGrad, AdaDelta, NoOp — as serializable
dataclasses that materialize optax transforms. Defaults match the reference's
constants (e.g. Adam eps 1e-8, Nesterovs momentum 0.9, RmsProp decay 0.95).

Where the reference applies updaters through ``UpdaterBlock`` views over the
flat params vector, here one optax update runs over the whole params pytree
inside the jitted train step; per-layer updater overrides use
``optax.multi_transform`` (wired by the training engine).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Type, Union

import optax

from deeplearning4j_tpu.train.schedules import Schedule

_UPDATER_REGISTRY: Dict[str, Type["Updater"]] = {}


def register_updater(cls):
    _UPDATER_REGISTRY[cls.__name__] = cls
    return cls


@dataclasses.dataclass
class Updater:
    learning_rate: Union[float, Schedule] = 1e-3

    def _lr(self):
        """optax learning rate (float or schedule callable)."""
        if isinstance(self.learning_rate, Schedule):
            return self.learning_rate
        return float(self.learning_rate)

    def make(self) -> optax.GradientTransformation:
        raise NotImplementedError

    def to_dict(self) -> dict:
        d = {"@type": type(self).__name__}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            d[f.name] = v.to_dict() if isinstance(v, Schedule) else v
        return d

    @staticmethod
    def from_dict(d: dict) -> "Updater":
        d = dict(d)
        cls = _UPDATER_REGISTRY[d.pop("@type")]
        if isinstance(d.get("learning_rate"), dict):
            d["learning_rate"] = Schedule.from_dict(d["learning_rate"])
        return cls(**d)


@register_updater
@dataclasses.dataclass
class Sgd(Updater):
    def make(self):
        return optax.sgd(self._lr())


@register_updater
@dataclasses.dataclass
class Adam(Updater):
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def make(self):
        return optax.adam(self._lr(), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


@register_updater
@dataclasses.dataclass
class AdaMax(Adam):
    def make(self):
        return optax.adamax(self._lr(), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


@register_updater
@dataclasses.dataclass
class AMSGrad(Adam):
    def make(self):
        return optax.amsgrad(self._lr(), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


@register_updater
@dataclasses.dataclass
class Nadam(Adam):
    def make(self):
        return optax.nadam(self._lr(), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


@register_updater
@dataclasses.dataclass
class Nesterovs(Updater):
    learning_rate: Union[float, Schedule] = 0.1
    momentum: float = 0.9

    def make(self):
        return optax.sgd(self._lr(), momentum=self.momentum, nesterov=True)


@register_updater
@dataclasses.dataclass
class RmsProp(Updater):
    rms_decay: float = 0.95
    epsilon: float = 1e-8

    def make(self):
        return optax.rmsprop(self._lr(), decay=self.rms_decay, eps=self.epsilon)


@register_updater
@dataclasses.dataclass
class AdaGrad(Updater):
    epsilon: float = 1e-6

    def make(self):
        return optax.adagrad(self._lr(), eps=self.epsilon)


@register_updater
@dataclasses.dataclass
class AdaDelta(Updater):
    rho: float = 0.95
    epsilon: float = 1e-6

    def make(self):
        # AdaDelta has no base LR in the reference; learning_rate ignored (1.0)
        return optax.adadelta(1.0, rho=self.rho, eps=self.epsilon)


@register_updater
@dataclasses.dataclass
class NoOp(Updater):
    def make(self):
        return optax.set_to_zero()


def decoupled_weight_decay(wd: float, lr, mask=None) -> optax.GradientTransformation:
    """Decoupled (AdamW-style) weight decay: appended AFTER the updater, adds
    ``-lr_t * wd * param`` to the final update so the decay is NOT scaled by
    adaptive preconditioners (matches the reference's ``WeightDecay``
    regularization with ``applyLR=true``)."""
    import jax
    import jax.numpy as jnp

    def init(params):
        return {"count": jnp.zeros((), jnp.int32)}

    def update(updates, state, params=None):
        if params is None:
            raise ValueError("decoupled_weight_decay requires params")
        lr_t = lr(state["count"]) if callable(lr) else lr
        m = mask(params) if callable(mask) else mask

        def leaf(u, p, use):
            return u - lr_t * wd * p if use else u

        if m is None:
            new_updates = jax.tree.map(lambda u, p: u - lr_t * wd * p, updates, params)
        else:
            new_updates = jax.tree.map(leaf, updates, params, m)
        return new_updates, {"count": state["count"] + 1}

    return optax.GradientTransformation(init, update)


# ---- gradient normalization (reference org.deeplearning4j.nn.conf.GradientNormalization) ----

def gradient_normalization_transform(kind: Optional[str], threshold: float = 1.0
                                     ) -> Optional[optax.GradientTransformation]:
    """Map the reference's GradientNormalization enum to an optax transform
    applied before the updater (the reference applies it in BaseLayer update)."""
    if not kind:
        return None
    k = kind.lower()
    if k in ("clipelementwiseabsolutevalue", "clip_element_wise_absolute_value"):
        return optax.clip(threshold)
    if k in ("clipl2perlayer", "clip_l2_per_layer", "clipl2perparamtype", "clip_l2_per_param_type"):
        # per-leaf L2 clip (param-type granularity — our leaves ARE param types)
        def clip_leaf(g):
            import jax.numpy as jnp
            norm = jnp.sqrt(jnp.sum(g * g))
            scale = jnp.minimum(1.0, threshold / (norm + 1e-12))
            return g * scale
        import jax
        return optax.stateless(lambda updates, params=None: jax.tree.map(clip_leaf, updates))
    if k in ("renormalizel2perlayer", "renormalize_l2_per_layer",
             "renormalizel2perparamtype", "renormalize_l2_per_param_type"):
        def renorm_leaf(g):
            import jax.numpy as jnp
            norm = jnp.sqrt(jnp.sum(g * g))
            return g / (norm + 1e-12)
        import jax
        return optax.stateless(lambda updates, params=None: jax.tree.map(renorm_leaf, updates))
    if k in ("clipglobalnorm", "clip_global_norm"):  # parity-plus: modern global-norm clip
        return optax.clip_by_global_norm(threshold)
    raise ValueError(f"Unknown gradient normalization {kind!r}")
