"""Multi-process data-parallel trainer with threshold-encoded gradient
exchange (ISSUE 6 — the reference's ``SharedTrainingMaster`` + Aeron
encoded-update path, SURVEY §L6).

The reference's signature scaling feature: Spark workers compute local
gradients, threshold-encode them (Strom 2015 — sparse 1-bit updates, the
un-sent remainder accumulating in a local *residual*), and exchange the
sparse encodings over Aeron; every worker decodes every peer's contribution
and applies the combined update. Here the same wire format
(:mod:`deeplearning4j_tpu.native` ``ThresholdCodec``) rides jax's gloo CPU
collectives (``runtime.mesh.initialize_multihost``) instead of Aeron, and
the combined update goes through the net's own optax updater chain —
the existing updater/solver machinery, not a side-channel SGD.

Layers:

- :class:`GradientExchange` — codec + transport. Each step the worker's
  local gradient contribution (scaled by ``1/world``) is threshold-encoded
  (sparse sign-index or 2-bit bitmap, whichever is *predicted* smaller —
  the choice must precede encoding because the residual is stateful),
  framed with a CRC32 header, allgathered in two phases (sizes, then
  payloads padded to the round's max), CRC-verified and decode-accumulated
  in rank order. ``threshold == 0`` selects the dense f32 transport (the
  encoded format degenerates to ±0 contributions there, so dense is the
  correctness fallback, exactly as the issue specifies). A corrupted or
  failed exchange raises :class:`ExchangeError` — never a silent
  divergence.
- :class:`DistributedTrainer` — the per-process step loop: local gradients
  via the AOT step path (PR 5's :class:`~deeplearning4j_tpu.runtime
  .compile_cache.AotCache`), exchange, combined update through
  ``net._tx``, periodic parameter re-broadcast from rank 0 to bound
  drift, crash-safe checkpoints with per-rank residual state and exact
  batch-level resume.
- :class:`DistributedSupervisor` — the multihost analog of
  :class:`~deeplearning4j_tpu.train.fault_tolerance.FaultTolerantTrainer`.
  An SPMD step is all-or-nothing: one lost worker stalls every peer in the
  collective, so supervision must sit ABOVE the process group — the
  supervisor watches per-worker heartbeat files with the same
  :class:`~deeplearning4j_tpu.train.fault_tolerance.HeartbeatMonitor`,
  and on a worker death *or* a stalled straggler kills the whole group,
  re-forms the mesh on a fresh coordinator port and relaunches within the
  same restart budget semantics; workers restore the newest valid
  checkpoint and resume at the exact batch.

Determinism contract (the correctness anchor): every worker iterates the
SAME deterministic global-batch iterator and slices its rank's shard, so
the single-process oracle is this very class in *loopback* mode
(``rank=None``): one process simulates all ranks' gradient computations
with the same jitted functions, per-rank codecs and the same rank-order
combine — the N-process trajectory must (and is tested to) match it
bit-for-bit, at threshold 0 and above.

Chaos points: ``train.distributed.exchange`` fires once per step at the
top of the exchange (fail → the worker dies → supervised restart);
``train.distributed.exchange.bytes`` passes the encoded payload through
byte corruption — the CRC check turns injected wire corruption into an
:class:`ExchangeError`, proving the no-silent-divergence property.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import struct
import subprocess
import threading
import time
import zlib
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.native import TreeCodec
from deeplearning4j_tpu.runtime import chaos, trace
from deeplearning4j_tpu.runtime.compile_cache import AotCache
from deeplearning4j_tpu.runtime.profiler import ExchangeStats
from deeplearning4j_tpu.train.checkpoint import (CheckpointListener,
                                                 atomic_save_model,
                                                 load_manifest,
                                                 write_manifest)
from deeplearning4j_tpu.train.fault_tolerance import (HeartbeatMonitor,
                                                      TrainingFailure)

logger = logging.getLogger(__name__)

_HEADER = struct.Struct("<iiIf")  # format, payload nbytes, crc32, local loss


class ExchangeError(RuntimeError):
    """A gradient exchange failed or arrived corrupted. Fatal to the step:
    the worker must die and be restarted from a checkpoint rather than
    train on a partial or garbage combined update."""


# --------------------------------------------------------------------------
# process-group plumbing shared by the supervisor, tests and bench
def free_port() -> str:
    """An OS-assigned free TCP port for the jax.distributed coordinator."""
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return str(s.getsockname()[1])


def worker_env(extra: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Environment for a CPU multihost worker subprocess: strips the
    TPU-plugin bootstrap and device-count flags (``sitecustomize``
    initialises the backend at interpreter start, which must not happen
    before ``jax.distributed.initialize``) and puts the repo on
    ``PYTHONPATH`` — the contract the round-6 multihost tests proved."""
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
           and not k.startswith("PALLAS_AXON")}
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    if extra:
        env.update(extra)
    return env


_children_lock = threading.Lock()  # guards: (_children pid registry)
_children: List[subprocess.Popen] = []


def _track_child(proc: subprocess.Popen) -> None:
    with _children_lock:
        _children.append(proc)


def live_worker_pids() -> List[int]:
    """PIDs of worker subprocesses launched through this module that are
    still alive — the conftest leak guard polls this after every test so
    no orphaned gloo worker survives a test."""
    with _children_lock:
        _children[:] = [p for p in _children if p.poll() is None]
        return [p.pid for p in _children]


def kill_stray_workers() -> List[int]:
    """Kill any still-live tracked workers (leak-guard teardown); returns
    the PIDs that had to be killed."""
    with _children_lock:
        stray = [p for p in _children if p.poll() is None]
        for p in stray:
            try:
                p.kill()
            except OSError:
                pass
        for p in stray:
            try:
                p.wait(timeout=10)
            except Exception:
                pass
        _children[:] = [p for p in _children if p.poll() is None]
    return [p.pid for p in stray]


# --------------------------------------------------------------------------
# transports
class CollectiveExchange:
    """Real multi-process transport over jax's collectives (gloo on CPU,
    ICI/DCN on TPU). Pure data movement — no arithmetic happens in the
    collective, so gathers are bit-exact and rank-order combination on the
    host is deterministic."""

    def __init__(self):
        import jax
        self._jax = jax
        from jax.experimental import multihost_utils
        self._mu = multihost_utils
        self.world = jax.process_count()
        self.rank = jax.process_index()

    def gather_bytes(self, payload: bytes) -> List[bytes]:
        """Allgather one variable-length byte payload per process. Two
        phases: sizes first, then payloads padded to the round's max —
        the wire cost is ``max_nbytes``, not the dense size."""
        sizes = self._mu.process_allgather(
            np.asarray([len(payload)], np.int64))
        sizes = np.asarray(sizes).reshape(-1)
        cap = int(sizes.max())
        buf = np.zeros(max(cap, 1), np.uint8)
        buf[:len(payload)] = np.frombuffer(payload, np.uint8)
        # single-process allgather returns the array without a process
        # axis; normalize to (world, cap)
        gathered = np.asarray(
            self._mu.process_allgather(buf)).reshape(self.world, -1)
        return [gathered[p, :int(sizes[p])].tobytes()
                for p in range(self.world)]

    def broadcast(self, arr: np.ndarray) -> np.ndarray:
        """Rank 0's array to everyone (parameter re-sync)."""
        return np.asarray(self._mu.broadcast_one_to_all(arr))

    def barrier(self, name: str) -> None:
        self._mu.sync_global_devices(name)


class LoopbackExchange:
    """Single-process stand-in: the trainer in oracle mode hands it every
    simulated rank's payload at once; gathers and broadcasts are list ops.
    Exists so the N-process trajectory has an executable bit-exact
    reference (and so chaos drills on the exchange run tier-1)."""

    def __init__(self, world: int):
        self.world = int(world)
        self.rank = 0

    def gather_bytes(self, payloads: List[bytes]) -> List[bytes]:
        if len(payloads) != self.world:
            raise ExchangeError(
                f"loopback gather got {len(payloads)} payloads for "
                f"world={self.world}")
        return list(payloads)

    def broadcast(self, arr: np.ndarray) -> np.ndarray:
        return arr

    def barrier(self, name: str) -> None:
        pass


# --------------------------------------------------------------------------
# the codec + transport layer
class GradientExchange:
    """Threshold-encoded gradient combine over a transport.

    One instance per *rank state* (a worker owns one; the loopback oracle
    owns one per simulated rank so residuals accumulate exactly as they
    would in the real processes). The wire frame is
    ``<header: format int32, nbytes int32, crc32 uint32, loss f32>``
    followed by the encoded payload; the CRC is computed from the intended
    payload *before* the ``train.distributed.exchange.bytes`` chaos point,
    so injected corruption is exactly what the receiver-side check
    catches."""

    def __init__(self, codec: TreeCodec, stats: Optional[ExchangeStats] = None):
        self.codec = codec
        self.stats = stats or ExchangeStats()
        self.threshold = codec.threshold

    @property
    def dense(self) -> bool:
        return self.threshold == 0.0

    def make_payload(self, flat_contribution: np.ndarray,
                     loss: float) -> bytes:
        """Encode one rank's scaled gradient contribution into a framed
        payload (mutates that rank's residual)."""
        t0 = time.perf_counter()
        if self.dense:
            fmt = TreeCodec.FORMAT_DENSE
            payload = np.ascontiguousarray(
                flat_contribution, np.float32).tobytes()
        else:
            fmt, payload = self.codec.encode(flat_contribution)
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        payload = chaos.transform_bytes(
            "train.distributed.exchange.bytes", payload)
        self.stats.record("encode", time.perf_counter() - t0)
        return _HEADER.pack(fmt, len(payload), crc, float(loss)) + payload

    def combine(self, frames: Sequence[bytes]) -> Tuple[np.ndarray, float]:
        """CRC-check every rank's frame and decode-accumulate in rank
        order. Returns ``(combined flat update, mean loss)`` — identical
        bits on every rank and in the loopback oracle."""
        t0 = time.perf_counter()
        combined = np.zeros(self.codec.size, np.float32)
        loss_sum = 0.0
        for p, frame in enumerate(frames):
            if len(frame) < _HEADER.size:
                raise ExchangeError(
                    f"short exchange frame from rank {p}: {len(frame)} bytes")
            fmt, nbytes, crc, loss = _HEADER.unpack(frame[:_HEADER.size])
            payload = frame[_HEADER.size:]
            if len(payload) != nbytes:
                raise ExchangeError(
                    f"rank {p} frame declares {nbytes} payload bytes, "
                    f"carries {len(payload)}")
            if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                raise ExchangeError(
                    f"CRC mismatch in rank {p}'s encoded update — "
                    f"corrupted exchange")
            if fmt == TreeCodec.FORMAT_DENSE:
                contrib = np.frombuffer(payload, np.float32)
                if contrib.size != self.codec.size:
                    raise ExchangeError(
                        f"rank {p} dense frame has {contrib.size} elements, "
                        f"expected {self.codec.size}")
                combined += contrib
            else:
                self.codec.decode_into(fmt, payload, combined)
            loss_sum += loss
        self.stats.record("decode", time.perf_counter() - t0)
        return combined, loss_sum / max(1, len(frames))


# --------------------------------------------------------------------------
# trainer
@dataclasses.dataclass
class DistributedConfig:
    """Knobs for :class:`DistributedTrainer`.

    ``threshold`` is in units of the *scaled* per-rank contribution
    (local gradient / world) — 0.0 selects the dense transport.
    ``resync_every`` re-broadcasts rank 0's parameters every N steps to
    bound drift (0 disables). ``checkpoint_every`` steps between
    crash-safe checkpoints (0 disables; rank 0 writes the model archive,
    every rank persists its own codec residual so a restart resumes the
    encoded stream exactly)."""

    threshold: float = 1e-3
    resync_every: int = 0
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 0
    keep_last: int = 3
    heartbeat_file: Optional[str] = None
    #: ISSUE 20 — encode/exchange vs compute overlap. 0 (default) is the
    #: fully synchronous schedule. 1 enables a one-deep in-flight window:
    #: step k+1's local gradients are computed (and encoded) BEFORE step
    #: k's allgather result is combined and applied, hiding the wire
    #: latency behind the next step's compute. This is an explicit
    #: staleness-1 delayed-update schedule — a DIFFERENT trajectory from
    #: window 0 — and the loopback oracle runs the exact same schedule,
    #: so worker-vs-oracle bit-identity holds at any window setting.
    overlap_window: int = 0


class DistributedTrainer:
    """Data-parallel trainer: N lock-step ranks exchanging
    threshold-encoded gradient updates.

    Worker mode (``world > 1`` inside an ``initialize_multihost`` process
    group, or ``world=1`` standalone): ``fit`` consumes a deterministic
    iterator of GLOBAL batches, slices this rank's shard, computes local
    gradients through the AOT step path, exchanges, and applies the
    combined update through the net's updater chain.

    Loopback-oracle mode (``rank=None``): the same class simulates every
    rank in one process — per-rank model state and codec residuals, the
    same jitted executables, the same rank-order combine — producing the
    bit-exact single-process reference trajectory the multi-process run
    is tested against.

    The net must expose MultiLayerNetwork's step surface
    (``_loss(params, model_state, x, y, rng, fmask, lmask)``, ``_tx``,
    ``_apply_constraints``); single-(x, y) workloads only — the
    multi-input ComputationGraph fit path is future work.
    """

    def __init__(self, net, config: Optional[DistributedConfig] = None,
                 world: Optional[int] = None, rank: Optional[int] = -1,
                 profiler=None, plan=None):
        import jax
        self._jax = jax
        self.net = net
        self.config = config or DistributedConfig()
        if self.config.overlap_window not in (0, 1):
            raise ValueError("overlap_window supports 0 (synchronous) or 1 "
                             "(one-deep in-flight exchange window)")
        self.stats = ExchangeStats()
        self.profiler = profiler
        if profiler is not None:
            profiler.attach_exchange(self.stats)
        self.loopback = rank is None
        if self.loopback:
            if not world or world < 1:
                raise ValueError("loopback mode needs an explicit world size")
            self.world = int(world)
            self.rank = 0
            self.transport = LoopbackExchange(self.world)
        else:
            self.transport = CollectiveExchange()
            self.world = self.transport.world if world is None else int(world)
            self.rank = self.transport.rank if rank == -1 else int(rank)
            if self.world != self.transport.world:
                raise ValueError(
                    f"world={self.world} but jax.process_count() is "
                    f"{self.transport.world}")
        if net.train_state is None:
            net.init()
        # ISSUE 20: an optional ParallelPlan shards the LOCAL step across
        # this process's devices (fsdp/tensor — the cross-process data
        # axis stays the threshold-encoded host exchange, so the combined
        # update is still exchanged ONLY over the data dimension). Pipe
        # plans belong to ParallelWrapper.fit / serving, not here.
        self.plan = plan
        if plan is not None:
            if getattr(plan, "pipe_size", 1) > 1:
                raise NotImplementedError(
                    "DistributedTrainer shards the local step with "
                    "fsdp/tensor axes; pipeline plans train through "
                    "ParallelWrapper.fit")
            from deeplearning4j_tpu.parallel.sharding import shard_train_state
            net.train_state = shard_train_state(net.train_state, plan)
        self._leaves, self._treedef = jax.tree.flatten(net.train_state.params)
        template = [np.asarray(l) for l in self._leaves]
        n_rank_states = self.world if self.loopback else 1
        self._exchanges = [
            GradientExchange(TreeCodec(template, self.config.threshold),
                             stats=self.stats)
            for _ in range(n_rank_states)]
        # per-rank model state: BN running stats etc. evolve from the LOCAL
        # shard (reference semantics too); rank 0's is the state of record
        self._rank_model_states = [net.train_state.model_state
                                   for _ in range(n_rank_states)]
        self._grad_aot = AotCache("distributed.grad")
        self._apply_aot = AotCache("distributed.apply")
        self._grad_fn = None
        self._apply_fn = None
        self.losses: List[float] = []
        # one-deep in-flight exchange window (ISSUE 20, overlap_window=1)
        self._inflight = None
        self._last_mean_loss: Optional[float] = None
        self._xchg_thread: Optional[threading.Thread] = None
        self._xchg_req = None
        self._xchg_res = None
        self._epoch_start_iters: Dict[int, int] = {}
        if self.config.checkpoint_dir:
            os.makedirs(self.config.checkpoint_dir, exist_ok=True)
            self._epoch_start_iters = self._load_epoch_starts()

    # ----------------------------------------------------------- jitted fns
    def _make_grad_fn(self):
        jax = self._jax

        def grad_step(params, model_state, x, y, rng):
            (loss, (new_state, _)), grads = jax.value_and_grad(
                self.net._loss, has_aux=True)(
                    params, model_state, x, y, rng, None, None)
            return loss, grads, new_state

        return jax.jit(grad_step)

    def _make_apply_fn(self):
        import optax

        jax = self._jax
        sizes = [int(np.prod(s)) if s else 1
                 for s in (np.shape(l) for l in self._leaves)]
        offsets = np.cumsum([0] + sizes).tolist()
        shapes = [np.shape(l) for l in self._leaves]
        dtypes = [l.dtype for l in self._leaves]

        def apply_step(ts, model_state, flat_update):
            leaves = [flat_update[lo:lo + sz].reshape(shape).astype(dt)
                      for lo, sz, shape, dt in
                      zip(offsets, sizes, shapes, dtypes)]
            grads = jax.tree.unflatten(self._treedef, leaves)
            updates, new_opt = self.net._tx.update(
                grads, ts.opt_state, ts.params)
            new_params = self.net._apply_constraints(
                optax.apply_updates(ts.params, updates))
            return dataclasses.replace(
                ts, params=new_params, model_state=model_state,
                opt_state=new_opt, step=ts.step + 1)

        return jax.jit(apply_step, donate_argnums=(0,))

    def _local_grad(self, rank_ix: int, x, y, rng):
        """One rank's local (loss, flat scaled gradient, new model state)
        through the AOT dispatch path."""
        if self._grad_fn is None:
            self._grad_fn = self._make_grad_fn()
        if self.plan is not None:
            # commit the local shard to the plan's batch axes so the grad
            # step runs plan-sharded; XLA's psum over those axes IS the
            # within-process reduction, the host exchange stays data-only
            jnp_x = self._jax.device_put(
                np.asarray(x), self.plan.batch_sharding(np.ndim(x)))
            jnp_y = self._jax.device_put(
                np.asarray(y), self.plan.batch_sharding(np.ndim(y)))
        else:
            jnp_x = self._jax.numpy.asarray(x)
            jnp_y = self._jax.numpy.asarray(y)
        key = (tuple(jnp_x.shape), str(jnp_x.dtype), tuple(jnp_y.shape),
               self.plan.signature() if self.plan is not None else None)
        loss, grads, new_state = self._grad_aot.call(
            key, self._grad_fn, self.net.train_state.params,
            self._rank_model_states[rank_ix], jnp_x, jnp_y, rng)
        self._rank_model_states[rank_ix] = new_state
        ex = self._exchanges[rank_ix]
        flat = ex.codec.flatten(
            [np.asarray(g) for g in self._jax.tree.leaves(grads)])
        # scale BEFORE encoding so the decode-accumulated sum approximates
        # the MEAN gradient — same LR semantics as the dense path
        flat /= np.float32(self.world)
        return float(loss), flat, ex

    def _apply(self, combined: np.ndarray) -> None:
        if self._apply_fn is None:
            self._apply_fn = self._make_apply_fn()
        t0 = time.perf_counter()
        ts = self._apply_aot.call(
            (self.plan.signature() if self.plan is not None else None,),
            self._apply_fn, self.net.train_state,
            self._rank_model_states[0], combined)
        if self.plan is not None:
            # re-commit the plan's parameter placement: the combined
            # update arrives replicated, and GSPMD's output choice for
            # params must not drift step over step (the AOT grad
            # executable was compiled against the plan layout)
            ts = dataclasses.replace(
                ts, params=self._jax.device_put(
                    ts.params, self.plan.param_sharding(ts.params)))
        self.net.train_state = ts
        self.stats.record("apply", time.perf_counter() - t0)

    # ----------------------------------------------------------------- step
    def step(self, x: np.ndarray, y: np.ndarray) -> float:
        """One lock-step distributed step over one GLOBAL batch. Returns
        the combined (mean-of-ranks) loss.

        Tracing (ISSUE 9): each step runs inside a ``train.step`` span —
        the :class:`ExchangeStats` stage hooks stamp the encode /
        exchange / decode / apply split onto it as stage events, a chaos
        fault at ``train.distributed.exchange`` is stamped by the
        injector, and tail sampling keeps exactly the interesting steps."""
        with trace.span("train.step") as tsp:
            if tsp.recording:
                tsp.set("rank", "loopback" if self.loopback else self.rank)
                tsp.set("world", self.world)
                tsp.set("step", int(self.net._iteration) + 1)
            return self._step_inner(x, y)

    def _step_inner(self, x: np.ndarray, y: np.ndarray) -> float:
        b = x.shape[0]
        if b % self.world:
            raise ValueError(f"global batch of {b} not divisible by "
                             f"world={self.world}")
        n_local = b // self.world
        rng = self.net.rng.next_key()
        chaos.inject("train.distributed.exchange")
        if self.loopback:
            send = []
            lsum = 0.0
            for r in range(self.world):
                lo = r * n_local
                loss, flat, ex = self._local_grad(
                    r, x[lo:lo + n_local], y[lo:lo + n_local], rng)
                send.append(ex.make_payload(flat, loss))
                lsum += loss
            loss = lsum / self.world
        else:
            lo = self.rank * n_local
            loss, flat, ex = self._local_grad(
                0, x[lo:lo + n_local], y[lo:lo + n_local], rng)
            send = ex.make_payload(flat, loss)
        handle = self._begin_gather(send)
        if self.config.overlap_window:
            # staleness-1 schedule (ISSUE 20): this step's allgather
            # drains behind the NEXT step's compute; what gets combined
            # and applied here is the PREVIOUS step's exchange. The first
            # step has nothing to apply yet — it returns the local loss
            # (in loopback, the mean over simulated ranks, which is the
            # exact value the eventual combine will report).
            prev, self._inflight = self._inflight, handle
            mean_loss = (self._complete_exchange(prev)
                         if prev is not None else float(loss))
        else:
            mean_loss = self._complete_exchange(handle)
        step_no = int(self.net._iteration) + 1
        self.net._iteration = step_no
        self.net._score = mean_loss
        if (self.config.resync_every
                and step_no % self.config.resync_every == 0):
            self.flush()
            self.resync_params()
        if (self.config.checkpoint_every and self.config.checkpoint_dir
                and step_no % self.config.checkpoint_every == 0):
            self.flush()
            self._checkpoint(step_no)
        if self.config.heartbeat_file:
            self._beat(step_no)
        return mean_loss

    # --------------------------------------------------- overlapped exchange
    def _exchange_worker(self) -> None:
        while True:
            item = self._xchg_req.get()
            if item is None:
                return
            try:
                self._xchg_res.put(("ok", self.transport.gather_bytes(item)))
            except BaseException as e:
                self._xchg_res.put(("err", e))

    def _begin_gather(self, send):
        """Dispatch one step's allgather. Loopback's gather is a list op —
        it completes inline; worker mode hands the frame to the exchange
        thread so the collective drains behind the next step's compute."""
        sent = len(send[0]) if isinstance(send, list) else len(send)
        if self.loopback or not self.config.overlap_window:
            t0 = time.perf_counter()
            frames = self.transport.gather_bytes(send)
            self.stats.record("exchange", time.perf_counter() - t0)
            return {"frames": frames, "sent": sent}
        if self._xchg_thread is None:
            import queue
            self._xchg_req = queue.Queue()
            self._xchg_res = queue.Queue()
            self._xchg_thread = threading.Thread(
                target=self._exchange_worker, name="dist-exchange",
                daemon=True)
            self._xchg_thread.start()
        self._xchg_req.put(send)
        return {"frames": None, "sent": sent}

    def _complete_exchange(self, handle) -> float:
        frames = handle["frames"]
        if frames is None:
            t0 = time.perf_counter()
            status, payload = self._xchg_res.get()
            # the recorded exchange time is the WAIT, not the wire time —
            # the overlap benefit shows up as this going to ~0
            self.stats.record("exchange", time.perf_counter() - t0)
            if status == "err":
                raise payload
            frames = payload
        dense_bytes = 4 * self._exchanges[0].codec.size
        # the two-phase gather pads every rank's send to the round max
        wire = max(len(f) for f in frames)
        self.stats.record_bytes(dense_bytes, wire, handle["sent"])
        combined, mean_loss = self._exchanges[0].combine(frames)
        self._apply(combined)
        self.losses.append(mean_loss)
        self._last_mean_loss = mean_loss
        return mean_loss

    def flush(self) -> Optional[float]:
        """Combine + apply any in-flight exchange (``overlap_window`` > 0).
        Runs before every checkpoint/resync and at fit end, so persisted
        or broadcast state never straddles a pending update. Returns the
        applied mean loss, or ``None`` when nothing was pending."""
        if self._inflight is None:
            return None
        handle, self._inflight = self._inflight, None
        return self._complete_exchange(handle)

    def close(self) -> None:
        """Join the overlap exchange thread (no-op when never started)."""
        if self._xchg_thread is not None:
            self._xchg_req.put(None)
            self._xchg_thread.join(timeout=10)
            self._xchg_thread = None

    def resync_params(self) -> None:
        """Re-broadcast rank 0's parameters to every rank — the periodic
        drift bound. A no-op by value when ranks are in lock-step (and in
        loopback mode), but it makes the lock-step invariant *enforced*
        rather than assumed on long runs."""
        jax = self._jax
        ex = self._exchanges[0]
        leaves = [np.asarray(l)
                  for l in jax.tree.leaves(self.net.train_state.params)]
        flat = ex.codec.flatten(leaves)
        synced = self.transport.broadcast(flat)
        if synced is not flat:
            new_leaves = [
                self._jax.numpy.asarray(a.astype(l.dtype))
                for a, l in zip(ex.codec.unflatten(synced), leaves)]
            self.net.train_state = dataclasses.replace(
                self.net.train_state,
                params=jax.tree.unflatten(self._treedef, new_leaves))

    # ------------------------------------------------------------------ fit
    def fit(self, iterator, epochs: int = 1):
        """Supervised epoch loop over a deterministic GLOBAL-batch
        iterator (every rank holds an identical copy — the multi-host
        data contract the round-6 tests established). Resumes exactly:
        with a checkpoint directory, a restarted worker restores the
        newest valid archive + its own residual, and skips the already
        trained leading batches of the in-progress epoch."""
        if self.profiler is not None:
            self.profiler.start()
        try:
            while self.net._epoch < int(epochs):
                e = int(self.net._epoch)
                start_iter = self._epoch_start_iters.get(e)
                if start_iter is None:
                    self._epoch_start_iters[e] = int(self.net._iteration)
                    self._save_epoch_starts()
                    skip = 0
                else:
                    skip = max(0, int(self.net._iteration) - start_iter)
                iterator.reset()
                seen = 0
                while iterator.has_next():
                    ds = iterator.next()
                    seen += 1
                    if seen <= skip:
                        continue  # deterministic replay into the void
                    t0 = time.perf_counter()
                    x = np.asarray(ds.features)
                    y = np.asarray(ds.labels)
                    if self.profiler is not None:
                        self.profiler.record_data_wait(
                            time.perf_counter() - t0)
                        t1 = time.perf_counter()
                        loss = self.step(x, y)
                        # synchronous loop: "dispatch" is the whole step
                        # (same as PR 4's unpipelined fit path) and the
                        # async step stage is deliberately NOT recorded —
                        # step_measured=False flags it as synchronous
                        self.profiler.record_dispatch(
                            time.perf_counter() - t1)
                    else:
                        loss = self.step(x, y)
                    for lst in self.net._listeners:
                        lst.iteration_done(self.net, self.net._iteration,
                                           self.net._epoch, loss)
                self.net._epoch = e + 1
                self.flush()
        finally:
            self.close()
            if self.profiler is not None:
                self.profiler.stop()
        return self.net

    # ---------------------------------------------------------- persistence
    def _beat(self, step_no: int) -> None:
        try:
            with open(self.config.heartbeat_file, "w") as f:
                f.write(str(step_no))
        except OSError:
            logger.warning("could not write heartbeat %s",
                           self.config.heartbeat_file)

    def _residual_path(self, rank: int, step_no: int) -> str:
        return os.path.join(self.config.checkpoint_dir,
                            f"exchange_r{rank}_s{step_no}.npz")

    def _checkpoint(self, step_no: int) -> None:
        """Crash-safe, group-consistent checkpoint. Order matters: every
        rank persists its residual for this step FIRST, then a barrier,
        then rank 0 commits the model archive — so a committed archive at
        step k implies every rank's residual for step k is durable."""
        cfg = self.config
        ranks = range(self.world) if self.loopback else [self.rank]
        for r in ranks:
            ex = self._exchanges[r if self.loopback else 0]
            path = self._residual_path(r, step_no)
            tmp = path + f".tmp.{os.getpid()}.npz"
            np.savez(tmp, residual=ex.codec.residual, step=step_no)
            os.replace(tmp, path)
        self.transport.barrier(f"ckpt-residuals-{step_no}")
        if self.loopback or self.rank == 0:
            archive = os.path.join(cfg.checkpoint_dir,
                                   f"checkpoint_{step_no}_dist.zip")
            entry = atomic_save_model(self.net, archive)
            manifest = load_manifest(cfg.checkpoint_dir)
            manifest[os.path.basename(archive)] = entry
            write_manifest(cfg.checkpoint_dir, manifest)
            self._prune(step_no)
        self.transport.barrier(f"ckpt-archive-{step_no}")

    def _prune(self, newest_step: int) -> None:
        cfg = self.config
        steps = sorted({s for s in (
            _dist_checkpoint_step(f) for f in os.listdir(cfg.checkpoint_dir))
            if s is not None})
        manifest = load_manifest(cfg.checkpoint_dir)
        changed = False
        for s in steps[:-max(1, cfg.keep_last)]:
            for f in os.listdir(cfg.checkpoint_dir):
                if _dist_checkpoint_step(f) == s:
                    changed |= manifest.pop(f, None) is not None
                    try:
                        os.unlink(os.path.join(cfg.checkpoint_dir, f))
                    except OSError:
                        pass
        if changed:
            write_manifest(cfg.checkpoint_dir, manifest)

    def restore(self) -> bool:
        """Restore the newest valid checkpoint (if any): model archive
        into the net, this rank's residual into the codec. Returns True
        when a checkpoint was restored."""
        cfg = self.config
        if not cfg.checkpoint_dir:
            return False
        ckpt = CheckpointListener.last_checkpoint_in(cfg.checkpoint_dir)
        if ckpt is None:
            return False
        logger.warning("rank %d restoring from %s", self.rank, ckpt)
        net = type(self.net).load(ckpt)
        self.net.train_state = net.train_state
        self.net._tx = net._tx
        self.net._iteration = net._iteration
        self.net._epoch = net._epoch
        self.net.rng = net.rng
        self._jit_reset()
        step_no = int(net._iteration)
        ranks = range(self.world) if self.loopback else [self.rank]
        for r in ranks:
            path = self._residual_path(r, step_no)
            ex = self._exchanges[r if self.loopback else 0]
            try:
                blob = np.load(path)
                if int(blob["step"]) != step_no:
                    raise ValueError("stale residual")
                ex.codec.residual = np.ascontiguousarray(
                    blob["residual"], np.float32)
            except (OSError, ValueError, KeyError):
                if not ex.dense:
                    raise TrainingFailure(
                        f"rank {r}: no residual state for checkpoint step "
                        f"{step_no} — cannot exact-resume the encoded "
                        f"stream") from None
        # model state of record is the restored archive's; a restart can
        # never inherit an in-flight exchange window
        self._inflight = None
        self._last_mean_loss = None
        if self.plan is not None:
            from deeplearning4j_tpu.parallel.sharding import shard_train_state
            self.net.train_state = shard_train_state(self.net.train_state,
                                                     self.plan)
        self._rank_model_states = [self.net.train_state.model_state
                                   for _ in self._rank_model_states]
        self._epoch_start_iters = self._load_epoch_starts()
        return True

    def _jit_reset(self) -> None:
        self._grad_fn = None
        self._apply_fn = None
        self._grad_aot.clear()
        self._apply_aot.clear()

    def _epoch_starts_path(self) -> str:
        return os.path.join(self.config.checkpoint_dir, "trainer_state.json")

    def _load_epoch_starts(self) -> Dict[int, int]:
        try:
            with open(self._epoch_starts_path()) as f:
                return {int(k): int(v) for k, v in
                        json.load(f)["epoch_start_iters"].items()}
        except (OSError, ValueError, KeyError, TypeError):
            return {}

    def _save_epoch_starts(self) -> None:
        if not self.config.checkpoint_dir:
            return
        if self.rank != 0 and not self.loopback:
            return
        path = self._epoch_starts_path()
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump({"epoch_start_iters": self._epoch_start_iters}, f)
            os.replace(tmp, path)
        except OSError:
            logger.warning("could not persist trainer state to %s", path)


def _dist_checkpoint_step(filename: str) -> Optional[int]:
    """Step number of a distributed checkpoint artifact (model archive or
    residual), else None."""
    if filename.startswith("checkpoint_") and filename.endswith("_dist.zip"):
        mid = filename[len("checkpoint_"):-len("_dist.zip")]
        return int(mid) if mid.isdigit() else None
    if filename.startswith("exchange_r") and filename.endswith(".npz"):
        parts = filename[:-len(".npz")].split("_s")
        return int(parts[-1]) if parts[-1].isdigit() else None
    return None


# --------------------------------------------------------------------------
# supervisor
class DistributedSupervisor:
    """Launch + watch + restart a local multi-process training group — the
    process-group analog of
    :class:`~deeplearning4j_tpu.train.fault_tolerance.FaultTolerantTrainer`
    (same :class:`HeartbeatMonitor`, same restart-budget escalation), one
    level up: a lost worker stalls every peer inside the collective, so
    recovery is always *kill the group, re-form the mesh on a fresh
    coordinator port, relaunch, restore the newest checkpoint*.

    ``make_argv(rank, port)`` returns the full worker argv (the worker
    script calls ``initialize_multihost`` with that port and runs a
    :class:`DistributedTrainer`). Heartbeat files are written by the
    workers (``DistributedConfig.heartbeat_file``); a worker making step
    progress beats the monitor, so both crashes (exit codes) and stalled
    stragglers (stale heartbeats while processes are alive) trigger a
    restart round."""

    def __init__(self, make_argv: Callable[[int, str], List[str]],
                 num_processes: int, heartbeat_files: Sequence[str],
                 max_restarts: int = 3,
                 restart_window_s: Optional[float] = None,
                 heartbeat_timeout_s: float = 120.0,
                 poll_s: float = 0.2,
                 env: Optional[Dict[str, str]] = None):
        self.make_argv = make_argv
        self.num_processes = int(num_processes)
        self.heartbeat_files = [str(h) for h in heartbeat_files]
        self.max_restarts = int(max_restarts)
        self.restart_window_s = restart_window_s
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.poll_s = float(poll_s)
        self.env = env
        self.restarts = 0
        self._restart_times: deque = deque()
        self.rounds: List[Dict[str, object]] = []

    # ------------------------------------------------------------- plumbing
    def _launch(self, port: str) -> List[subprocess.Popen]:
        """Spawn one worker per rank. Output goes to temp FILES, not
        pipes: the supervisor doesn't drain during a round, and a worker
        producing more than the OS pipe buffer would block mid-step and
        read as a stalled straggler."""
        import tempfile
        env = self.env if self.env is not None else worker_env()
        procs = []
        for rank in range(self.num_processes):
            out_f = tempfile.NamedTemporaryFile(
                mode="w+", prefix=f"dl4j-dist-r{rank}-out-", delete=False)
            err_f = tempfile.NamedTemporaryFile(
                mode="w+", prefix=f"dl4j-dist-r{rank}-err-", delete=False)
            p = subprocess.Popen(
                self.make_argv(rank, port), env=env, text=True,
                stdout=out_f, stderr=err_f)
            p._dl4j_capture = (out_f, err_f)  # type: ignore[attr-defined]
            _track_child(p)
            procs.append(p)
        return procs

    @staticmethod
    def _collect(p: subprocess.Popen) -> Tuple[str, str]:
        """Reap one exited worker and return its (stdout, stderr)."""
        try:
            p.wait(timeout=60)
        except Exception:
            p.kill()
        texts = []
        for f in getattr(p, "_dl4j_capture", ()):
            try:
                f.flush()
                f.seek(0)
                texts.append(f.read())
            except (OSError, ValueError):
                texts.append("")
            finally:
                try:
                    f.close()
                    os.unlink(f.name)
                except OSError:
                    pass
        return tuple(texts) if len(texts) == 2 else ("", "")

    @classmethod
    def _kill_group(cls, procs: List[subprocess.Popen]
                    ) -> List[Tuple[str, str]]:
        for p in procs:
            if p.poll() is None:
                p.kill()
        return [cls._collect(p) for p in procs]

    def _register_restart(self, cause: str) -> None:
        now = time.monotonic()
        self.restarts += 1
        self._restart_times.append(now)
        if self.restart_window_s is not None:
            while (self._restart_times and
                   now - self._restart_times[0] > self.restart_window_s):
                self._restart_times.popleft()
            recent = len(self._restart_times)
            budget = (f"{self.max_restarts} restarts in "
                      f"{self.restart_window_s:.0f}s")
        else:
            recent = self.restarts
            budget = f"{self.max_restarts} restarts"
        if recent > self.max_restarts:
            raise TrainingFailure(
                f"distributed training giving up after {budget} "
                f"(last cause: {cause})")
        logger.warning("distributed group failed (%s); restart %d within "
                       "budget %s", cause, recent, budget)

    # ------------------------------------------------------------------ run
    def run(self, round_timeout_s: float = 600.0) -> List[Tuple[str, str]]:
        """Supervise until one launch round finishes cleanly (every worker
        exits 0) or the restart budget is exhausted
        (:class:`TrainingFailure`). Returns the successful round's
        per-rank ``(stdout, stderr)``."""
        while True:
            port = free_port()
            procs = self._launch(port)
            monitor = HeartbeatMonitor(self.heartbeat_timeout_s)
            seen: Dict[int, float] = {}
            cause = None
            deadline = time.monotonic() + round_timeout_s
            try:
                while True:
                    for i, hb in enumerate(self.heartbeat_files):
                        try:
                            m = os.stat(hb).st_mtime
                        except OSError:
                            continue
                        if seen.get(i) != m:
                            seen[i] = m
                            monitor.beat()  # any worker progressing = alive
                    codes = [p.poll() for p in procs]
                    if any(c not in (None, 0) for c in codes):
                        cause = (f"worker exited with codes "
                                 f"{[c for c in codes if c is not None]}")
                        break
                    if all(c == 0 for c in codes):
                        outs = [self._collect(p) for p in procs]
                        self.rounds.append(
                            {"port": port, "outcome": "success"})
                        return outs
                    # without heartbeat files there is no straggler signal
                    # — exit codes are the only failure detector, and an
                    # un-beaten monitor must not kill a healthy group
                    if self.heartbeat_files:
                        try:
                            monitor.check()
                        except TrainingFailure as e:
                            cause = f"stalled group: {e}"
                            break
                    if time.monotonic() > deadline:
                        cause = f"round timeout after {round_timeout_s:.0f}s"
                        break
                    time.sleep(self.poll_s)
            finally:
                if cause is not None:
                    self._kill_group(procs)
            self.rounds.append({"port": port, "outcome": cause})
            self._register_restart(cause)
