"""Numerical gradient checking.

Rebuild of upstream ``org.deeplearning4j.gradientcheck.GradientCheckUtil`` /
``org.nd4j.autodiff.validation.GradCheckUtil`` (SURVEY.md §4): compare the
training loss's analytic gradients (``jax.grad`` of the composed network)
against central finite differences, parameter-by-parameter, in float64.

Because backprop here is autodiff of the same forward that computes the loss
(not hand-written per-layer backward like the reference), this check
validates the *forward* semantics: masking, preprocessors, regularization
terms, and loss fusion — the places where a framework bug can hide.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np


class GradientCheckUtil:
    @staticmethod
    def check_gradients(net, features, labels, *, epsilon: float = 1e-4,
                        max_rel_error: float = 1e-2, abs_error_floor: float = 1e-6,
                        max_per_param: int = 5, fmask=None, lmask=None,
                        seed: int = 0, print_results: bool = False) -> bool:
        """Sample up to ``max_per_param`` coordinates of every parameter
        tensor; returns True iff all pass. ``net`` must be initialised.

        Runs in float64 (like the reference, which checks in double): x64 is
        enabled for the duration and params/inputs/compute dtype are upcast,
        since float32 FD noise at eps=1e-4 swamps a 1e-2 tolerance."""
        from deeplearning4j_tpu.runtime.environment import get_environment
        x64_was = jax.config.jax_enable_x64
        env = get_environment()
        cdt_was = env.compute_dtype
        jax.config.update("jax_enable_x64", True)
        env.compute_dtype = jnp.float64
        try:
            return GradientCheckUtil._check_f64(
                net, features, labels, epsilon=epsilon,
                max_rel_error=max_rel_error, abs_error_floor=abs_error_floor,
                max_per_param=max_per_param, fmask=fmask, lmask=lmask,
                seed=seed, print_results=print_results)
        finally:
            env.compute_dtype = cdt_was
            jax.config.update("jax_enable_x64", x64_was)
            net._jit_cache.clear()  # drop f64-traced functions

    @staticmethod
    def _check_f64(net, features, labels, *, epsilon, max_rel_error,
                   abs_error_floor, max_per_param, fmask, lmask, seed,
                   print_results) -> bool:
        def up(a):
            a = jnp.asarray(a)
            return a.astype(jnp.float64) if jnp.issubdtype(a.dtype, jnp.floating) else a

        x = up(features)
        y = up(labels)
        fmask = None if fmask is None else up(fmask)
        lmask = None if lmask is None else up(lmask)
        params = jax.tree.map(up, net.train_state.params)
        model_state = jax.tree.map(up, net.train_state.model_state)

        def loss_fn(p):
            # dropout off / deterministic path for checkable gradients
            loss, _ = net._loss(p, model_state, x, y, None,
                                fmask, lmask, training=False)
            return loss

        analytic = jax.grad(loss_fn)(params)
        flat, _ = jax.tree_util.tree_flatten_with_path(params)
        rng = np.random.default_rng(seed)
        ok = True
        for path, leaf in flat:
            keys = tuple(str(getattr(p, "key", p)) for p in path)
            a_leaf = np.asarray(_get_path(analytic, path), np.float64)
            leaf_np = np.asarray(leaf, np.float64)
            n = leaf_np.size
            picks = rng.choice(n, size=min(max_per_param, n), replace=False)
            for flat_idx in picks:
                idx = np.unravel_index(flat_idx, leaf_np.shape)
                fd = GradientCheckUtil._fd(loss_fn, params, path, idx, epsilon)
                an = a_leaf[idx]
                denom = max(abs(fd), abs(an), 1e-10)
                rel = abs(fd - an) / denom
                passed = rel < max_rel_error or abs(fd - an) < abs_error_floor
                if print_results or not passed:
                    print(f"  {'/'.join(keys)}[{idx}]: analytic={an:.6g} "
                          f"fd={fd:.6g} rel={rel:.3g} {'OK' if passed else 'FAIL'}")
                ok = ok and passed
        return ok

    @staticmethod
    def _fd(loss_fn, params, path, idx, eps):
        def perturbed(delta):
            leaf = _get_path(params, path)
            new_leaf = jnp.asarray(leaf).at[idx].add(delta)
            return _set_path(params, path, new_leaf)

        lp = float(loss_fn(perturbed(+eps)))
        lm = float(loss_fn(perturbed(-eps)))
        return (lp - lm) / (2 * eps)


def _get_path(tree, path):
    cur = tree
    for p in path:
        key = getattr(p, "key", getattr(p, "idx", None))
        cur = cur[key]
    return cur


def _set_path(tree, path, value):
    if not path:
        return value
    p, rest = path[0], path[1:]
    key = getattr(p, "key", getattr(p, "idx", None))
    if isinstance(tree, dict):
        out = dict(tree)
        out[key] = _set_path(tree[key], rest, value)
        return out
    if isinstance(tree, (list, tuple)):
        out = list(tree)
        out[key] = _set_path(tree[key], rest, value)
        return type(tree)(out)
    raise TypeError(f"Cannot set path into {type(tree)}")
