"""Early stopping.

Rebuild of upstream ``org.deeplearning4j.earlystopping``: an
``EarlyStoppingConfiguration`` of termination conditions + score calculator,
driven by an ``EarlyStoppingTrainer`` that keeps the best model seen and
returns an ``EarlyStoppingResult``. Same decomposition as the reference:

- epoch termination: ``MaxEpochsTerminationCondition``,
  ``ScoreImprovementEpochTerminationCondition``,
  ``BestScoreEpochTerminationCondition``
- iteration termination: ``MaxTimeIterationTerminationCondition``,
  ``MaxScoreIterationTerminationCondition`` (NaN/explosion guard)
- score calculator: ``DataSetLossCalculator`` (validation loss) or any
  callable ``net -> float`` (lower is better, as in the reference)
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, List, Optional


class DataSetLossCalculator:
    """Validation loss over an iterator (reference ``DataSetLossCalculator``,
    average=true: example-weighted mean loss)."""

    def __init__(self, iterator):
        self.iterator = iterator

    def __call__(self, net) -> float:
        total, n = 0.0, 0
        self.iterator.reset()
        for batch in self.iterator:
            total += float(net.score(batch)) * len(batch)
            n += len(batch)
        return total / max(n, 1)


# ---- epoch termination conditions ----
class MaxEpochsTerminationCondition:
    requires_score = False  # checked every epoch, scored or not

    def __init__(self, max_epochs: int):
        self.max_epochs = max_epochs

    def terminate(self, epoch: int, score: float, best_score: float) -> bool:
        return epoch + 1 >= self.max_epochs


class ScoreImprovementEpochTerminationCondition:
    """Stop after ``max_epochs_without_improvement`` non-improving epochs
    (optionally requiring at least ``min_improvement``)."""

    requires_score = True

    def __init__(self, max_epochs_without_improvement: int,
                 min_improvement: float = 0.0):
        self.patience = max_epochs_without_improvement
        self.min_improvement = min_improvement
        self._best = float("inf")
        self._stale = 0

    def initialize(self) -> None:
        """Reset run-scoped state (called by the trainer at fit start so a
        condition instance can be reused across runs)."""
        self._best = float("inf")
        self._stale = 0

    def terminate(self, epoch: int, score: float, best_score: float) -> bool:
        if score < self._best - self.min_improvement:
            self._best = score
            self._stale = 0
        else:
            self._stale += 1
        return self._stale > self.patience


class BestScoreEpochTerminationCondition:
    """Stop once the score is at/below a target (reference semantics:
    'good enough')."""

    requires_score = True

    def __init__(self, target_score: float):
        self.target_score = target_score

    def terminate(self, epoch: int, score: float, best_score: float) -> bool:
        return score <= self.target_score


# ---- iteration termination conditions ----
class MaxTimeIterationTerminationCondition:
    def __init__(self, max_seconds: float):
        self.max_seconds = max_seconds
        self._start: Optional[float] = None

    def start(self) -> None:
        self._start = time.monotonic()

    def terminate(self, score: float) -> bool:
        return (time.monotonic() - (self._start or time.monotonic())) \
            >= self.max_seconds


class MaxScoreIterationTerminationCondition:
    """Abort if the minibatch score exceeds a bound or goes NaN (the
    reference's divergence guard)."""

    def __init__(self, max_score: float):
        self.max_score = max_score

    def start(self) -> None:
        pass

    def terminate(self, score: float) -> bool:
        return not (score == score) or score > self.max_score


@dataclasses.dataclass
class EarlyStoppingConfiguration:
    score_calculator: Callable[[Any], float] = None
    epoch_termination_conditions: List[Any] = dataclasses.field(default_factory=list)
    iteration_termination_conditions: List[Any] = dataclasses.field(default_factory=list)
    evaluate_every_n_epochs: int = 1
    save_last_model: bool = False

    class Builder:
        def __init__(self):
            self._kw = dict(epoch_termination_conditions=[],
                            iteration_termination_conditions=[])

        def score_calculator(self, calc):
            self._kw["score_calculator"] = calc
            return self

        def epoch_termination_conditions(self, *conds):
            self._kw["epoch_termination_conditions"] = list(conds)
            return self

        def iteration_termination_conditions(self, *conds):
            self._kw["iteration_termination_conditions"] = list(conds)
            return self

        def evaluate_every_n_epochs(self, n: int):
            self._kw["evaluate_every_n_epochs"] = int(n)
            return self

        def save_last_model(self, save: bool = True):
            self._kw["save_last_model"] = bool(save)
            return self

        def build(self) -> "EarlyStoppingConfiguration":
            return EarlyStoppingConfiguration(**self._kw)

    @staticmethod
    def builder() -> "EarlyStoppingConfiguration.Builder":
        return EarlyStoppingConfiguration.Builder()


@dataclasses.dataclass
class EarlyStoppingResult:
    termination_reason: str  # "EpochTerminationCondition" | "IterationTerminationCondition" | "Error"
    termination_details: str
    total_epochs: int
    best_model_epoch: int
    best_model_score: float
    score_vs_epoch: dict
    best_model: Any
    last_model: Any = None  # populated when config.save_last_model


class EarlyStoppingTrainer:
    """Reference ``EarlyStoppingTrainer``: epoch loop with score evaluation,
    best-model retention, and both condition families."""

    def __init__(self, config: EarlyStoppingConfiguration, net, train_iterator):
        self.config = config
        self.net = net
        self.iterator = train_iterator

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        needs_clone = cfg.save_last_model or cfg.score_calculator is not None
        if needs_clone and not hasattr(self.net, "clone"):
            raise ValueError(
                "best/last-model retention needs net.clone(); implement it, "
                "or drop the score calculator / save_last_model")
        if not hasattr(self.net, "set_listeners"):
            raise ValueError(
                "EarlyStoppingTrainer needs the TrainingListener API "
                "(set_listeners/get_listeners) on the network")
        if cfg.score_calculator is None:
            scored = [type(c).__name__ for c in cfg.epoch_termination_conditions
                      if getattr(c, "requires_score", True)]
            if scored:
                # score-gated conditions would be skipped every epoch -> the
                # loop could never terminate
                raise ValueError(
                    f"conditions {scored} need a score_calculator")
        best_score, best_epoch = float("inf"), -1
        best_params = None
        scores = {}
        for c in cfg.iteration_termination_conditions:
            c.start()

        class _IterGuard:
            """Listener checking iteration conditions on every minibatch."""

            details = ""

            def __init__(self, conds):
                self.conds = conds

            def on_epoch_start(self, net, epoch):
                pass

            def on_epoch_end(self, net, epoch):
                pass

            def iteration_done(self, net, iteration, epoch, score):
                for c in self.conds:
                    if c.terminate(float(score)):
                        self.details = type(c).__name__
                        raise StopIteration(self.details)

        for c in cfg.epoch_termination_conditions:
            if hasattr(c, "initialize"):
                c.initialize()

        guard = _IterGuard(cfg.iteration_termination_conditions)
        epoch = 0
        reason, details = "EpochTerminationCondition", ""
        old_listeners = list(self.net.get_listeners()) \
            if hasattr(self.net, "get_listeners") else []
        self.net.set_listeners(*(old_listeners + [guard]))  # checked above
        last_score = float("nan")
        try:
            while True:
                try:
                    self.net.fit(self.iterator, epochs=1)
                except StopIteration:
                    reason = "IterationTerminationCondition"
                    details = guard.details
                    break
                if cfg.score_calculator is not None and \
                        (epoch + 1) % cfg.evaluate_every_n_epochs == 0:
                    last_score = float(cfg.score_calculator(self.net))
                    scores[epoch] = last_score
                    if last_score < best_score:
                        best_score, best_epoch = last_score, epoch
                        # deep-copy the buffers: the live train_state is
                        # DONATED at the next step, which would delete a
                        # shallow snapshot's arrays
                        best_params = self._snapshot_state()
                # Score-free conditions (requires_score=False, e.g.
                # MaxEpochs) run EVERY epoch so they never overshoot; all
                # others — including user-defined ones — keep the original
                # contract of running only on fresh-score epochs (a stale/
                # NaN score would count as non-improvement).
                fresh = epoch in scores
                stop = False
                for c in cfg.epoch_termination_conditions:
                    if getattr(c, "requires_score", True) and not fresh:
                        continue
                    if c.terminate(epoch, last_score, best_score):
                        details = type(c).__name__
                        stop = True
                        break
                if stop:
                    break
                epoch += 1
        finally:
            self.net.set_listeners(*old_listeners)

        last_model = None
        if cfg.save_last_model:
            last_model = self._clone_with(self._snapshot_state())
        best_model = self.net
        if best_params is not None:
            best_model = self._clone_with(best_params)
        return EarlyStoppingResult(
            termination_reason=reason, termination_details=details,
            total_epochs=epoch + 1, best_model_epoch=best_epoch,
            best_model_score=best_score, score_vs_epoch=scores,
            best_model=best_model, last_model=last_model)

    def _snapshot_state(self):
        import jax
        import jax.numpy as jnp
        return jax.tree.map(
            lambda a: jnp.array(a, copy=True) if hasattr(a, "dtype") else a,
            self.net.train_state)

    def _clone_with(self, state):
        model = self.net.clone()  # presence validated at fit() start
        model.train_state = state
        return model
