"""Step-time profiler for the overlapped training pipeline.

``bench.py --serving`` proved the serving overlap win with measured stage
latencies; this is the training-side counterpart. A :class:`TrainingProfiler`
attached to ``fit(..., profiler=...)`` (MultiLayerNetwork, ComputationGraph,
ParallelWrapper) splits every iteration's wall time into the three pipeline
stages:

- **data wait** — time the consumer loop spent blocked waiting for the next
  coerced batch (the whole ETL+transfer cost when synchronous; the queue
  wait when a :class:`~deeplearning4j_tpu.train.prefetch.DevicePrefetcher`
  hides it),
- **dispatch** — host time to issue the jitted step (and grouped-dispatch
  bookkeeping) — jax async dispatch returns before the device finishes,
- **step** — submit→loss-ready latency, observed on the completion path
  (async loss readback), where syncing is free because dispatch is not
  waiting on it.

``report()['data_wait_fraction']`` is the headline number: the fraction of
fit wall time the device spent starved for data. The overlap win is thereby
*observable* (sync fit shows the ETL fraction; prefetched fit shows it
collapsing toward 0), not asserted. Histograms reuse
:class:`~deeplearning4j_tpu.serving.metrics.LatencyHistogram` — one
percentile implementation across training and serving.

Thread-safety: stages are recorded from the fit loop, the prefetch worker
and the completion worker concurrently; all mutation is behind one lock.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from deeplearning4j_tpu.runtime import trace


class TrainingProfiler:
    """Per-iteration stage timing for ``fit``. Attach one instance per fit
    call (``net.fit(it, profiler=TrainingProfiler())``); read
    :meth:`report` after fit returns."""

    STAGES = ("data_wait", "dispatch", "step")

    def __init__(self):
        from deeplearning4j_tpu.serving.metrics import LatencyHistogram
        # guards: _totals, _counts, _hists, _t_start, _t_stop
        self._lock = threading.Lock()
        self._hists = {s: LatencyHistogram() for s in self.STAGES}
        self._totals = {s: 0.0 for s in self.STAGES}
        self._counts = {s: 0 for s in self.STAGES}
        self._t_start: Optional[float] = None
        self._t_stop: Optional[float] = None
        self._exchange = None  # ExchangeStats from a DistributedTrainer

    def attach_exchange(self, stats) -> "TrainingProfiler":
        """Attach a :class:`~deeplearning4j_tpu.runtime.profiler.ExchangeStats`
        (the distributed trainer does this when handed a profiler): its
        encode/exchange/decode/apply split and compression counters merge
        into :meth:`report` under ``exchange_*`` keys and onto the
        :meth:`summary` headline."""
        self._exchange = stats
        return self

    # ------------------------------------------------------------ recording
    def start(self) -> "TrainingProfiler":
        """Mark the window start (``fit`` calls this; explicit calls allow
        profiling a sub-window)."""
        with self._lock:
            if self._t_start is None:
                self._t_start = time.perf_counter()
        return self

    def stop(self) -> "TrainingProfiler":
        with self._lock:
            self._t_stop = time.perf_counter()
        return self

    def _record(self, stage: str, seconds: float) -> None:
        # stage split onto the active span, when one is open in this
        # thread (ISSUE 9) — the trace-tree view of the same numbers
        trace.stage_event(stage, seconds)
        with self._lock:
            if self._t_start is None:
                self._t_start = time.perf_counter() - seconds
            self._totals[stage] += seconds
            self._counts[stage] += 1
            self._hists[stage].observe(seconds)

    def record_data_wait(self, seconds: float) -> None:
        self._record("data_wait", seconds)

    def record_dispatch(self, seconds: float) -> None:
        self._record("dispatch", seconds)

    def record_step(self, seconds: float) -> None:
        self._record("step", seconds)

    # ------------------------------------------------------------ reporting
    @property
    def iterations(self) -> int:
        with self._lock:
            return self._counts["dispatch"]

    def elapsed(self) -> float:
        with self._lock:
            if self._t_start is None:
                return 0.0
            end = self._t_stop if self._t_stop is not None else time.perf_counter()
            return max(0.0, end - self._t_start)

    def report(self) -> Dict[str, float]:
        """Aggregate stage report. ``data_wait_fraction`` is data-wait time
        over the profiled wall-clock window; ``steps_per_sec`` counts
        dispatched iterations over the same window."""
        elapsed = self.elapsed()
        with self._lock:
            out: Dict[str, float] = {
                "iterations": self._counts["dispatch"],
                "elapsed_s": round(elapsed, 4),
            }
            for s in self.STAGES:
                n = self._counts[s]
                out[f"{s}_total_s"] = round(self._totals[s], 4)
                out[f"{s}_mean_ms"] = round(
                    self._totals[s] / n * 1e3, 3) if n else 0.0
                out[f"{s}_p99_ms"] = round(
                    self._hists[s].percentile(99) * 1e3, 3)
            out["data_wait_fraction"] = round(
                self._totals["data_wait"] / elapsed, 4) if elapsed else 0.0
            out["steps_per_sec"] = round(
                self._counts["dispatch"] / elapsed, 2) if elapsed else 0.0
            # the step stage is observed on the async completion path; a
            # state-reading listener forces synchronous delivery, where it
            # is never recorded — flag that rather than report 0 as "free"
            out["step_measured"] = self._counts["step"] > 0
        if self._exchange is not None:
            out["exchange"] = self._exchange.report()
        return out

    def summary(self) -> str:
        r = self.report()
        step = (f"step {r['step_mean_ms']:.2f}ms submit->ready"
                if r["step_measured"] else
                "step unmeasured (synchronous delivery)")
        line = (f"TrainingProfiler: {r['iterations']} iterations in "
                f"{r['elapsed_s']:.2f}s ({r['steps_per_sec']:.1f} steps/s); "
                f"data wait {r['data_wait_total_s']:.2f}s "
                f"({r['data_wait_fraction']:.0%} of wall), dispatch "
                f"{r['dispatch_mean_ms']:.2f}ms/iter, {step}")
        if self._exchange is not None:
            line += "; " + self._exchange.headline()
        return line


def submit_timed(gd, args, profiler: Optional[TrainingProfiler] = None) -> None:
    """``gd.submit(args)`` with optional dispatch timing — the one submit
    wrapper shared by the three fit loops (MultiLayerNetwork,
    ComputationGraph, ParallelWrapper)."""
    if profiler is None:
        gd.submit(args)
        return
    t0 = time.perf_counter()
    gd.submit(args)
    profiler.record_dispatch(time.perf_counter() - t0)
