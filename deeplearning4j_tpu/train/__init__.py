"""Training engine: updaters, LR schedules, listeners, gradient processing.

Rebuild of the reference's training stack: nd4j updaters
(``org.nd4j.linalg.learning``), LR schedules (``org.nd4j.linalg.schedule``),
the Solver/optimizer (``org.deeplearning4j.optimize.solvers``), and the
``TrainingListener`` SPI — re-architected so the whole optimizer update runs
inside the jitted train step (the reference's ``UpdaterBlock`` flat-view trick
becomes "one optax update over one pytree").
"""

from deeplearning4j_tpu.train.updaters import (
    AMSGrad,
    AdaDelta,
    AdaGrad,
    AdaMax,
    Adam,
    Nadam,
    Nesterovs,
    NoOp,
    RmsProp,
    Sgd,
    Updater,
)
from deeplearning4j_tpu.train.schedules import (
    CycleSchedule,
    ExponentialSchedule,
    InverseSchedule,
    MapSchedule,
    PolySchedule,
    Schedule,
    SigmoidSchedule,
    StepSchedule,
)
from deeplearning4j_tpu.train.listeners import (
    BaseTrainingListener,
    CollectScoresListener,
    EvaluativeListener,
    PerformanceListener,
    ScoreIterationListener,
    TrainingListener,
)
from deeplearning4j_tpu.train.fault_tolerance import (
    FaultTolerantTrainer,
    HeartbeatMonitor,
    TrainingFailure,
)
from deeplearning4j_tpu.train.prefetch import (
    AsyncLossDelivery,
    DevicePrefetcher,
    coerce_training_batch,
)
from deeplearning4j_tpu.train.profiler import TrainingProfiler
from deeplearning4j_tpu.train.distributed import (
    DistributedConfig,
    DistributedSupervisor,
    DistributedTrainer,
    ExchangeError,
)
from deeplearning4j_tpu.train.early_stopping import (
    BestScoreEpochTerminationCondition,
    DataSetLossCalculator,
    EarlyStoppingConfiguration,
    EarlyStoppingResult,
    EarlyStoppingTrainer,
    MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
)

__all__ = [
    "FaultTolerantTrainer",
    "HeartbeatMonitor",
    "TrainingFailure",
    "DevicePrefetcher", "AsyncLossDelivery", "coerce_training_batch",
    "TrainingProfiler",
    "DistributedTrainer", "DistributedConfig", "DistributedSupervisor",
    "ExchangeError",
    "Updater", "Sgd", "Adam", "AdaMax", "AMSGrad", "Nadam", "Nesterovs",
    "RmsProp", "AdaGrad", "AdaDelta", "NoOp",
    "Schedule", "StepSchedule", "ExponentialSchedule", "InverseSchedule",
    "PolySchedule", "SigmoidSchedule", "MapSchedule", "CycleSchedule",
    "TrainingListener", "BaseTrainingListener", "ScoreIterationListener",
    "PerformanceListener", "EvaluativeListener", "CollectScoresListener",
    "EarlyStoppingConfiguration", "EarlyStoppingTrainer", "EarlyStoppingResult",
    "DataSetLossCalculator", "MaxEpochsTerminationCondition",
    "ScoreImprovementEpochTerminationCondition",
    "BestScoreEpochTerminationCondition", "MaxTimeIterationTerminationCondition",
    "MaxScoreIterationTerminationCondition",
]
