"""``python -m deeplearning4j_tpu.analysis`` — run the project lint.

Exit status 1 on any finding (CI-friendly); ``--json`` emits the
machine-readable findings list the driver tooling consumes.
"""

import argparse
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.analysis",
        description="Project concurrency/observability invariant lint")
    ap.add_argument("--json", action="store_true",
                    help="emit machine-readable findings JSON")
    ap.add_argument("--root", default=None,
                    help="package root to lint (default: the installed "
                         "deeplearning4j_tpu package)")
    args = ap.parse_args(argv)

    from deeplearning4j_tpu.analysis import lint

    if args.root:
        findings = lint.run_lint(package_root=args.root)
    else:
        findings = lint.run_lint()
    if args.json:
        print(lint.to_json(findings))
    else:
        print(lint.render(findings))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
