"""AST-based project-invariant linter (stdlib ``ast``, no new deps).

The repo's concurrency and observability discipline lives in conventions
the review rounds kept re-checking by hand. This linter turns each into a
machine-checked invariant (run as the tier-1 test
``tests/test_analysis.py::test_repo_is_clean`` and as
``python -m deeplearning4j_tpu.analysis``):

- **THREAD-UNNAMED / THREAD-UNREGISTERED** — every ``threading.Thread``
  is named, and the name's static prefix is registered in
  ``analysis/registry.py:THREAD_NAME_PREFIXES`` (conftest's leak guard
  imports the same registry, so the two can never drift).
- **LOCK-UNDECLARED / GUARD-VIOLATION** — every ``threading.Lock`` /
  ``RLock`` / ``Condition`` assigned to an attribute carries an adjacent
  ``# guards:`` declaration, and no declared-guarded attribute is touched
  outside a ``with`` on its lock within the same class (intraprocedural;
  ``__init__`` is exempt — the object is not shared yet — and a method
  annotated ``# holds: <lock>`` declares its callers hold the lock).
- **CHAOS-UNREGISTERED / CHAOS-STALE / CHAOS-UNDOCUMENTED /
  CHAOS-UNTESTED** — every chaos point fired in code exists in
  ``runtime/chaos.py:REGISTERED_POINTS``, every registered point is
  fired somewhere, has a ``docs/robustness.md`` row, and appears in at
  least one test.
- **JOURNAL-UNREGISTERED / JOURNAL-STALE / JOURNAL-UNDOCUMENTED /
  JOURNAL-UNTESTED** — the same four-way diff over journal event types
  (ISSUE 15): every ``journal.emit("<type>", ...)`` site names a type in
  ``runtime/journal.py:EVENT_TYPES``, every registered type is emitted
  somewhere, documented in ``docs/observability.md``, and exercised by a
  test or bench drill.
- **ROUTE-UNDOCUMENTED** — every ``/v1/*`` route string appears in
  ``docs/observability.md`` (placeholders normalised to ``<name>``).
- **METRIC-UNDOCUMENTED / METRIC-NAMESPACE** — every Prometheus series
  the package renders (recognised by the ``name{labels} value`` /
  ``# TYPE name`` emission shape) is namespaced per
  ``registry.METRIC_NAMESPACES`` and documented in
  ``docs/observability.md``.
- **WIRE-UNMAPPED-HEADER / WIRE-STALE-FIELD** — every ``X-*`` control
  header (plus ``Retry-After``/``Retry-After-Ms``) used in the serving
  tier has a frame-field mapping in ``serving/wire.py:HEADER_FIELDS``,
  and every mapped header is still used somewhere (ISSUE 18): a new
  header can't silently lose its semantics on the binary path.
- **WALLCLOCK** — no ``time.time()`` / ``time.time_ns()`` and no stdlib
  ``random`` in trajectory-affecting modules
  (``registry.TRAJECTORY_MODULES``): inject a clock/RNG instead. Escape
  hatch for reviewed exceptions: ``# lint: wallclock-ok (<why>)`` on the
  line.

See ``docs/static_analysis.md`` for how to read findings and when an
allowlist/escape is acceptable.
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from deeplearning4j_tpu.analysis.registry import (
    METRIC_NAMESPACES,
    PIPELINE_THREAD_NAMES,
    THREAD_NAME_PREFIXES,
    TRAJECTORY_MODULES,
)

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_ROOT = os.path.dirname(_PKG_ROOT)

_PH = "\x00"  # placeholder marker for f-string holes in templates


class Finding:
    def __init__(self, code: str, path: str, line: int, message: str):
        self.code = code
        self.path = path
        self.line = int(line)
        self.message = message

    def __repr__(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"

    def to_dict(self) -> Dict[str, object]:
        return {"code": self.code, "path": self.path, "line": self.line,
                "message": self.message}


# --------------------------------------------------------------------- utils
def _template(node: ast.AST) -> Optional[str]:
    """A string Constant, or a JoinedStr flattened with ``\\x00`` marking
    each formatted hole — the shape checks run over this template."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append(_PH)
        return "".join(parts)
    return None


def _parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    par: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            par[child] = node
    return par


def _enclosing_function(node: ast.AST, par) -> Optional[ast.AST]:
    cur = par.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = par.get(cur)
    return None


def _is_threading_attr(func: ast.AST, names: Sequence[str]) -> Optional[str]:
    if (isinstance(func, ast.Attribute) and func.attr in names
            and isinstance(func.value, ast.Name)
            and func.value.id == "threading"):
        return func.attr
    return None


class _FileCtx:
    """One parsed file plus the comment-aware source-line helpers."""

    def __init__(self, rel_path: str, source: str):
        self.rel_path = rel_path
        self.source = source
        self.lines = source.split("\n")
        self.tree = ast.parse(source)
        self.par = _parents(self.tree)

    def line(self, n: int) -> str:
        return self.lines[n - 1] if 1 <= n <= len(self.lines) else ""

    def adjacent(self, n: int) -> str:
        """The line, the line above and the line below — the window a
        declaration comment may live in."""
        return "\n".join(self.line(i) for i in (n - 1, n, n + 1))


# ------------------------------------------------------------ thread naming
def _resolve_str_prefix(node: ast.AST, ctx: _FileCtx,
                        depth: int = 0) -> Optional[str]:
    """Best-effort static prefix of a string expression: constants,
    f-string heads, ``%``/``+`` left sides, and simple Name resolution
    through enclosing-function locals and parameter defaults."""
    if depth > 4 or node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        if not node.values:
            return None
        head = node.values[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str):
            return head.value
        if isinstance(head, ast.FormattedValue):
            return _resolve_str_prefix(head.value, ctx, depth + 1)
        return None
    if isinstance(node, ast.BinOp) and isinstance(node.op, (ast.Add, ast.Mod)):
        return _resolve_str_prefix(node.left, ctx, depth + 1)
    if isinstance(node, ast.Name):
        fn = _enclosing_function(node, ctx.par)
        # parameter default
        while fn is not None:
            args = fn.args
            pos = args.posonlyargs + args.args
            defaults = args.defaults
            for a, d in zip(pos[len(pos) - len(defaults):], defaults):
                if a.arg == node.id:
                    return _resolve_str_prefix(d, ctx, depth + 1)
            for a, d in zip(args.kwonlyargs, args.kw_defaults):
                if a.arg == node.id and d is not None:
                    return _resolve_str_prefix(d, ctx, depth + 1)
            # local assignment inside the function
            for sub in ast.walk(fn):
                if (isinstance(sub, ast.Assign)
                        and any(isinstance(t, ast.Name) and t.id == node.id
                                for t in sub.targets)):
                    return _resolve_str_prefix(sub.value, ctx, depth + 1)
            fn = _enclosing_function(fn, ctx.par)
        # module-level constant
        for sub in ctx.tree.body:
            if (isinstance(sub, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == node.id
                            for t in sub.targets)):
                return _resolve_str_prefix(sub.value, ctx, depth + 1)
    return None


def check_thread_names(ctx: _FileCtx,
                       prefixes: Sequence[str] = THREAD_NAME_PREFIXES
                       ) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if _is_threading_attr(node.func, ("Thread",)) is None:
            continue
        name_kw = next((k.value for k in node.keywords if k.arg == "name"),
                       None)
        if name_kw is None:
            out.append(Finding(
                "THREAD-UNNAMED", ctx.rel_path, node.lineno,
                "threading.Thread without name= — every thread must carry "
                "a registered name (analysis/registry.py)"))
            continue
        prefix = _resolve_str_prefix(name_kw, ctx)
        if prefix is None:
            out.append(Finding(
                "THREAD-UNREGISTERED", ctx.rel_path, node.lineno,
                "thread name is not statically resolvable — use a constant "
                "or f-string with a registered constant prefix"))
            continue
        if not any(prefix.startswith(p) for p in prefixes):
            out.append(Finding(
                "THREAD-UNREGISTERED", ctx.rel_path, node.lineno,
                f"thread name prefix {prefix!r} is not registered in "
                f"analysis/registry.py:THREAD_NAME_PREFIXES"))
    return out


# --------------------------------------------------------- lock declarations
_GUARDS_RE = re.compile(r"#\s*guards:\s*(.+?)\s*$", re.M)
_HOLDS_RE = re.compile(r"#\s*holds:\s*([\w, ]+)")


def _lock_assignments(ctx: _FileCtx):
    """Yield (assign_node, owner, attr, kind) for every
    ``<target> = threading.Lock()/RLock()/Condition()`` in the file.
    owner is the ClassDef for ``self.X`` targets, None for module/local."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        call = node.value
        if not isinstance(call, ast.Call):
            continue
        kind = _is_threading_attr(call.func, ("Lock", "RLock", "Condition"))
        if kind is None:
            continue
        tgt = node.targets[0]
        owner = None
        if (isinstance(tgt, ast.Attribute) and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"):
            cur = ctx.par.get(node)
            while cur is not None and not isinstance(cur, ast.ClassDef):
                cur = ctx.par.get(cur)
            owner = cur
            attr = tgt.attr
        elif isinstance(tgt, ast.Name):
            attr = tgt.id
        else:
            continue
        yield node, owner, attr, kind


def _parse_guards(decl: str) -> List[str]:
    """``# guards: _a, _b`` -> ["_a", "_b"]; ``# guards: (free text)`` ->
    [] (declared, but no machine-checkable attribute mapping)."""
    decl = decl.strip()
    if decl.startswith("("):
        return []
    return [a.strip() for a in decl.split(",") if a.strip()]


def check_lock_guards(ctx: _FileCtx) -> List[Finding]:
    out: List[Finding] = []
    # class -> {lock_attr: [guarded attrs]}
    class_locks: Dict[ast.ClassDef, Dict[str, List[str]]] = {}
    for node, owner, attr, kind in _lock_assignments(ctx):
        window = ctx.adjacent(node.lineno)
        m = _GUARDS_RE.search(window)
        if m is None:
            out.append(Finding(
                "LOCK-UNDECLARED", ctx.rel_path, node.lineno,
                f"threading.{kind} assigned to {attr!r} without an adjacent "
                f"'# guards:' declaration (list the attributes it guards, "
                f"or '# guards: (<what invariant it protects>)')"))
            continue
        if owner is not None:
            class_locks.setdefault(owner, {})[attr] = \
                _parse_guards(m.group(1))

    for cls, locks in class_locks.items():
        guarded: Dict[str, str] = {}      # attr -> lock attr
        for lock_attr, attrs in locks.items():
            for a in attrs:
                guarded[a] = lock_attr
        if not guarded:
            continue
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if meth.name == "__init__":
                continue
            held: Set[str] = set()
            m = _HOLDS_RE.search(ctx.adjacent(meth.lineno))
            if m:
                held |= {h.strip() for h in m.group(1).split(",") if h.strip()}
            out.extend(_check_method_guards(ctx, cls, meth, guarded, held))
    return out


def _with_locks(node: ast.With) -> Set[str]:
    got: Set[str] = set()
    for item in node.items:
        e = item.context_expr
        if (isinstance(e, ast.Attribute) and isinstance(e.value, ast.Name)
                and e.value.id == "self"):
            got.add(e.attr)
    return got


def _check_method_guards(ctx: _FileCtx, cls: ast.ClassDef, meth,
                         guarded: Dict[str, str],
                         held: Set[str]) -> List[Finding]:
    out: List[Finding] = []

    def visit(node, held_now: Set[str]):
        if isinstance(node, ast.With):
            held_now = held_now | _with_locks(node)
        elif (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self" and node.attr in guarded
                and guarded[node.attr] not in held_now):
            if "# unguarded-ok" not in ctx.line(node.lineno):
                out.append(Finding(
                    "GUARD-VIOLATION", ctx.rel_path, node.lineno,
                    f"{cls.name}.{meth.name} touches self.{node.attr} "
                    f"outside 'with self.{guarded[node.attr]}' (declared "
                    f"'# guards:' on that lock); annotate the def with "
                    f"'# holds: {guarded[node.attr]}' if callers hold it"))
        for child in ast.iter_child_nodes(node):
            visit(child, held_now)

    for stmt in meth.body:
        visit(stmt, set(held))
    return out


# ----------------------------------------------------------------- chaos
def parse_registered_points(chaos_source: str) -> Dict[str, str]:
    tree = ast.parse(chaos_source)
    for node in tree.body:
        if isinstance(node, ast.AnnAssign):
            targets = [node.target]
        elif isinstance(node, ast.Assign):
            targets = node.targets
        else:
            continue
        if (len(targets) == 1 and isinstance(targets[0], ast.Name)
                and targets[0].id == "REGISTERED_POINTS"
                and isinstance(node.value, ast.Dict)):
            points = {}
            for k, v in zip(node.value.keys, node.value.values):
                if (isinstance(k, ast.Constant) and isinstance(k.value, str)
                        and isinstance(v, ast.Constant)
                        and isinstance(v.value, str)):
                    points[k.value] = v.value
            return points
    return {}


def collect_fired_points(ctx: _FileCtx) -> List[Tuple[str, int]]:
    """Chaos points fired in this file: first args of
    ``chaos.inject(...)`` / ``chaos.transform_bytes(...)`` calls (module
    alias or bare ``inject``/``transform_bytes`` imported names),
    constants resolved through module-level names."""
    fired: List[Tuple[str, int]] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        f = node.func
        is_point_call = (
            (isinstance(f, ast.Attribute)
             and f.attr in ("inject", "transform_bytes")
             and isinstance(f.value, ast.Name) and f.value.id == "chaos")
            or (isinstance(f, ast.Name)
                and f.id in ("inject", "transform_bytes")))
        if not is_point_call:
            continue
        val = _resolve_str_prefix(node.args[0], ctx)
        if isinstance(node.args[0], ast.Constant) or val is not None:
            if val:
                fired.append((val, node.lineno))
    return fired


# ------------------------------------------------------------------- wire
#: control-header literal shape the wire registry diff scans for: the
#: ``X-*`` family plus the two Retry-After spellings the shed/backoff
#: path emits (the only non-``X-`` headers the protocol must carry)
_HEADER_LITERAL = re.compile(r'"(X-[A-Za-z][A-Za-z0-9-]*|Retry-After(?:-Ms)?)"')


def parse_header_fields(wire_source: str) -> Dict[str, str]:
    """The ``HEADER_FIELDS`` dict literal out of ``serving/wire.py``
    (same AST extraction as :func:`parse_registered_points`): HTTP
    control header -> binary frame field name."""
    tree = ast.parse(wire_source)
    for node in tree.body:
        if isinstance(node, ast.AnnAssign):
            targets = [node.target]
        elif isinstance(node, ast.Assign):
            targets = node.targets
        else:
            continue
        if (len(targets) == 1 and isinstance(targets[0], ast.Name)
                and targets[0].id == "HEADER_FIELDS"
                and isinstance(node.value, ast.Dict)):
            fields = {}
            for k, v in zip(node.value.keys, node.value.values):
                if (isinstance(k, ast.Constant) and isinstance(k.value, str)
                        and isinstance(v, ast.Constant)
                        and isinstance(v.value, str)):
                    fields[k.value] = v.value
            return fields
    return {}


# ---------------------------------------------------------------- journal
def parse_event_types(journal_source: str) -> Dict[str, str]:
    """The ``EVENT_TYPES`` dict literal out of ``runtime/journal.py``
    (same AST extraction as :func:`parse_registered_points`)."""
    tree = ast.parse(journal_source)
    for node in tree.body:
        if isinstance(node, ast.AnnAssign):
            targets = [node.target]
        elif isinstance(node, ast.Assign):
            targets = node.targets
        else:
            continue
        if (len(targets) == 1 and isinstance(targets[0], ast.Name)
                and targets[0].id == "EVENT_TYPES"
                and isinstance(node.value, ast.Dict)):
            types = {}
            for k, v in zip(node.value.keys, node.value.values):
                if (isinstance(k, ast.Constant) and isinstance(k.value, str)
                        and isinstance(v, ast.Constant)
                        and isinstance(v.value, str)):
                    types[k.value] = v.value
            return types
    return {}


def collect_emitted_types(ctx: _FileCtx) -> List[Tuple[str, int]]:
    """Journal event types emitted in this file: first args of
    ``journal.emit(...)`` calls (the required call spelling — emit sites
    import the module, not the function, so the linter can see them)."""
    emitted: List[Tuple[str, int]] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute) and f.attr == "emit"
                and isinstance(f.value, ast.Name)
                and f.value.id == "journal"):
            continue
        val = _resolve_str_prefix(node.args[0], ctx)
        if val:
            emitted.append((val, node.lineno))
    return emitted


# ----------------------------------------------------------------- routes
def collect_routes(ctx: _FileCtx) -> List[Tuple[str, int]]:
    routes: List[Tuple[str, int]] = []
    for node in ast.walk(ctx.tree):
        t = _template(node)
        if t is None or not t.startswith("/v1/"):
            continue
        norm = t.split("?", 1)[0]
        norm = norm.replace(_PH, "<name>").rstrip("/")
        if norm:
            routes.append((norm, node.lineno))
    return routes


# ---------------------------------------------------------------- metrics
_METRIC_HEAD = re.compile(r"^([a-z][a-z0-9_]*)")
_METRIC_SUFFIX_HEAD = re.compile(r"^\x00(_[a-z0-9_]+)")


def _looks_like_sample(rest: str) -> bool:
    """After the metric name: optional ``{labels}`` / placeholder label
    block, then a space and a value (placeholder or literal number)."""
    if rest.startswith("{"):
        close = rest.find("}")
        if close < 0:
            # f-string splits the label block across constants; treat a
            # trailing open brace as label-block-then-value elsewhere
            return True
        rest = rest[close + 1:]
    if rest.startswith(_PH):
        rest = rest[1:]
    if not rest.startswith(" "):
        return False
    rest = rest.lstrip(" ")
    return bool(rest) and (rest[0] == _PH or rest[0].isdigit()
                           or rest[0] == "-")


def collect_metric_names(ctx: _FileCtx) -> List[Tuple[str, int, bool]]:
    """(name, line, is_suffix) for every metric-sample-shaped string.
    ``is_suffix`` marks dynamic-prefix emissions (``f"{prefix}_x ..."``)
    where only the suffix is statically known."""
    found: List[Tuple[str, int, bool]] = []
    for node in ast.walk(ctx.tree):
        t = _template(node)
        if t is None:
            continue
        for raw in t.split("\n"):
            line = raw.strip()
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                parts = line.split(" ")
                if len(parts) >= 3 and _METRIC_HEAD.match(parts[2]):
                    found.append((parts[2], node.lineno, False))
                continue
            m = _METRIC_SUFFIX_HEAD.match(line)
            if m and _looks_like_sample(line[m.end():]):
                found.append((m.group(1), node.lineno, True))
                continue
            m = _METRIC_HEAD.match(line)
            if (m and "_" in m.group(1)
                    and _looks_like_sample(line[m.end():])):
                found.append((m.group(1), node.lineno, False))
    return found


# --------------------------------------------------------------- wallclock
def check_wallclock(ctx: _FileCtx) -> List[Finding]:
    top = ctx.rel_path.split("/", 1)[0]
    if top not in TRAJECTORY_MODULES:
        return []
    out: List[Finding] = []
    imports_random = any(
        (isinstance(n, ast.Import)
         and any(a.name == "random" for a in n.names))
        or (isinstance(n, ast.ImportFrom) and n.module == "random")
        for n in ast.walk(ctx.tree))
    for node in ast.walk(ctx.tree):
        bad = None
        if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)):
            if node.value.id == "time" and node.attr in ("time", "time_ns"):
                bad = f"time.{node.attr}"
            elif node.value.id == "random" and imports_random:
                bad = f"random.{node.attr}"
        if bad and "# lint: wallclock-ok" not in ctx.line(node.lineno):
            out.append(Finding(
                "WALLCLOCK", ctx.rel_path, node.lineno,
                f"{bad} in trajectory-affecting module — inject a "
                f"clock/RNG (or annotate '# lint: wallclock-ok (<why>)' "
                f"for a reviewed observability-only use)"))
    return out


# ------------------------------------------------------------------ runner
class Linter:
    """Whole-package run. Tests drive the per-file checks directly with
    synthetic sources via :meth:`lint_source`."""

    def __init__(self, package_root: str = _PKG_ROOT,
                 repo_root: str = _REPO_ROOT):
        self.package_root = package_root
        self.repo_root = repo_root
        self.findings: List[Finding] = []
        self._fired: List[Tuple[str, str, int]] = []   # (point, path, line)
        self._emitted: List[Tuple[str, str, int]] = []  # (etype, path, line)
        self._routes: List[Tuple[str, str, int]] = []
        self._metrics: List[Tuple[str, str, int, bool]] = []
        self._all_sources: Dict[str, str] = {}

    # ---------------------------------------------------------- file pass
    def lint_source(self, rel_path: str, source: str) -> List[Finding]:
        """Run every per-file check over one source blob; returns (and
        does not accumulate) the findings — the entry point the analyzer
        self-tests feed fixture snippets through."""
        ctx = _FileCtx(rel_path, source)
        findings = []
        findings += check_thread_names(ctx)
        findings += check_lock_guards(ctx)
        findings += check_wallclock(ctx)
        return findings

    def _file_pass(self, rel_path: str, source: str) -> None:
        try:
            ctx = _FileCtx(rel_path, source)
        except SyntaxError as e:
            self.findings.append(Finding("PARSE-ERROR", rel_path,
                                         e.lineno or 0, str(e)))
            return
        self.findings += check_thread_names(ctx)
        self.findings += check_lock_guards(ctx)
        self.findings += check_wallclock(ctx)
        for point, line in collect_fired_points(ctx):
            self._fired.append((point, rel_path, line))
        for etype, line in collect_emitted_types(ctx):
            self._emitted.append((etype, rel_path, line))
        for route, line in collect_routes(ctx):
            self._routes.append((route, rel_path, line))
        for name, line, is_suffix in collect_metric_names(ctx):
            self._metrics.append((name, rel_path, line, is_suffix))

    # --------------------------------------------------------- cross-file
    def _read(self, *parts) -> str:
        try:
            with open(os.path.join(self.repo_root, *parts)) as f:
                return f.read()
        except OSError:
            return ""

    def _cross_checks(self) -> None:
        chaos_src = self._all_sources.get("runtime/chaos.py", "")
        registered = parse_registered_points(chaos_src)
        robustness = self._read("docs", "robustness.md")
        observability = self._read("docs", "observability.md")
        tests_text = ""
        tests_dir = os.path.join(self.repo_root, "tests")
        if os.path.isdir(tests_dir):
            for fn in sorted(os.listdir(tests_dir)):
                if fn.endswith(".py"):
                    tests_text += self._read("tests", fn)
        bench_text = self._read("bench.py")

        for point, path, line in self._fired:
            if point not in registered:
                self.findings.append(Finding(
                    "CHAOS-UNREGISTERED", path, line,
                    f"chaos point {point!r} fired but absent from "
                    f"runtime/chaos.py:REGISTERED_POINTS"))
        pkg_text = "".join(self._all_sources.values())
        for point in registered:
            if point not in pkg_text:
                self.findings.append(Finding(
                    "CHAOS-STALE", "runtime/chaos.py", 0,
                    f"registered chaos point {point!r} never appears in "
                    f"package code"))
            if f"`{point}`" not in robustness:
                self.findings.append(Finding(
                    "CHAOS-UNDOCUMENTED", "runtime/chaos.py", 0,
                    f"registered chaos point {point!r} has no "
                    f"docs/robustness.md row"))
            if point not in tests_text and point not in bench_text:
                self.findings.append(Finding(
                    "CHAOS-UNTESTED", "runtime/chaos.py", 0,
                    f"registered chaos point {point!r} is exercised by no "
                    f"test or bench drill"))

        # journal event types: the same four-way parity as chaos points
        # (ISSUE 15) — emit sites <-> registry <-> docs table <-> drills
        journal_src = self._all_sources.get("runtime/journal.py", "")
        event_types = parse_event_types(journal_src)
        for etype, path, line in self._emitted:
            if etype not in event_types:
                self.findings.append(Finding(
                    "JOURNAL-UNREGISTERED", path, line,
                    f"journal event type {etype!r} emitted but absent "
                    f"from runtime/journal.py:EVENT_TYPES"))
        for etype in event_types:
            if not any(e == etype for e, _, _ in self._emitted):
                self.findings.append(Finding(
                    "JOURNAL-STALE", "runtime/journal.py", 0,
                    f"registered journal event type {etype!r} is emitted "
                    f"nowhere in package code"))
            if f"`{etype}`" not in observability:
                self.findings.append(Finding(
                    "JOURNAL-UNDOCUMENTED", "runtime/journal.py", 0,
                    f"registered journal event type {etype!r} has no "
                    f"docs/observability.md row"))
            if etype not in tests_text and etype not in bench_text:
                self.findings.append(Finding(
                    "JOURNAL-UNTESTED", "runtime/journal.py", 0,
                    f"registered journal event type {etype!r} is "
                    f"exercised by no test or bench drill"))

        for route, path, line in sorted(set(self._routes)):
            if route not in observability:
                self.findings.append(Finding(
                    "ROUTE-UNDOCUMENTED", path, line,
                    f"route {route!r} not documented in "
                    f"docs/observability.md"))

        doc_words = set(re.findall(r"[a-z][a-z0-9_]+", observability))
        for name, path, line, is_suffix in sorted(set(self._metrics)):
            if is_suffix:
                if not any(w.endswith(name) for w in doc_words):
                    self.findings.append(Finding(
                        "METRIC-UNDOCUMENTED", path, line,
                        f"dynamic-prefix metric '*{name}' has no "
                        f"documented name ending with that suffix in "
                        f"docs/observability.md"))
                continue
            if name.endswith("_"):
                # dynamic-suffix emission (f"fleet_capacity_{counter} ...")
                if not any(w.startswith(name) for w in doc_words):
                    self.findings.append(Finding(
                        "METRIC-UNDOCUMENTED", path, line,
                        f"dynamic-suffix metric '{name}*' has no "
                        f"documented name starting with that prefix in "
                        f"docs/observability.md"))
                continue
            if not name.startswith(METRIC_NAMESPACES):
                self.findings.append(Finding(
                    "METRIC-NAMESPACE", path, line,
                    f"metric {name!r} outside the registered namespaces "
                    f"(analysis/registry.py:METRIC_NAMESPACES)"))
                continue
            if name not in doc_words:
                self.findings.append(Finding(
                    "METRIC-UNDOCUMENTED", path, line,
                    f"metric {name!r} not documented in "
                    f"docs/observability.md"))

        # wire header<->frame-field registry diff (ISSUE 18): every X-*
        # control header the serving tier forwards must have a frame-field
        # mapping in serving/wire.py:HEADER_FIELDS — a header without one
        # would silently lose its semantics on the binary path — and every
        # mapped header must still exist somewhere in serving code
        wire_src = self._all_sources.get("serving/wire.py", "")
        header_fields = parse_header_fields(wire_src)
        serving_headers: Dict[str, Tuple[str, int]] = {}
        for rel in sorted(self._all_sources):
            if not rel.startswith("serving/") or rel == "serving/wire.py":
                continue
            src = self._all_sources[rel]
            for m in _HEADER_LITERAL.finditer(src):
                line = src.count("\n", 0, m.start()) + 1
                serving_headers.setdefault(m.group(1), (rel, line))
        for hdr, (path, line) in sorted(serving_headers.items()):
            if hdr not in header_fields:
                self.findings.append(Finding(
                    "WIRE-UNMAPPED-HEADER", path, line,
                    f"control header {hdr!r} used by the serving tier has "
                    f"no frame-field mapping in "
                    f"serving/wire.py:HEADER_FIELDS (the binary protocol "
                    f"would drop it)"))
        for hdr in header_fields:
            if hdr not in serving_headers:
                self.findings.append(Finding(
                    "WIRE-STALE-FIELD", "serving/wire.py", 0,
                    f"HEADER_FIELDS maps header {hdr!r} that no serving "
                    f"module outside wire.py references"))

        for name in PIPELINE_THREAD_NAMES:
            if name not in THREAD_NAME_PREFIXES:
                self.findings.append(Finding(
                    "REGISTRY-DRIFT", "analysis/registry.py", 0,
                    f"PIPELINE_THREAD_NAMES entry {name!r} missing from "
                    f"THREAD_NAME_PREFIXES"))

    # -------------------------------------------------------------- drive
    def run(self) -> List[Finding]:
        for root, dirs, files in os.walk(self.package_root):
            dirs[:] = sorted(d for d in dirs if d != "__pycache__")
            for fn in sorted(files):
                if not fn.endswith(".py"):
                    continue
                full = os.path.join(root, fn)
                rel = os.path.relpath(full, self.package_root).replace(
                    os.sep, "/")
                if rel.startswith("analysis/"):
                    continue      # the analyzer does not lint itself
                with open(full) as f:
                    src = f.read()
                self._all_sources[rel] = src
                self._file_pass(rel, src)
        self._cross_checks()
        self.findings.sort(key=lambda f: (f.path, f.line, f.code))
        return self.findings


def run_lint(package_root: str = _PKG_ROOT,
             repo_root: str = _REPO_ROOT) -> List[Finding]:
    return Linter(package_root, repo_root).run()


def render(findings: List[Finding]) -> str:
    if not findings:
        return "lint: clean"
    return "\n".join(repr(f) for f in findings) + \
        f"\n{len(findings)} finding(s)"


def to_json(findings: List[Finding]) -> str:
    return json.dumps({"findings": [f.to_dict() for f in findings],
                       "count": len(findings)}, indent=2)
