"""Runtime lock-order witness (the Python analog of kernel lockdep).

Twelve PRs of concurrent serving/training machinery rest on ~35 lock
sites whose ordering discipline was, until now, convention plus code
review. This module makes it machine-checked: with ``DL4J_TPU_LOCKDEP=1``
(the tier-1 conftest enables it for the whole suite),
``threading.Lock`` / ``RLock`` / ``Condition`` constructions **inside the
deeplearning4j_tpu package** return named, site-attributed proxies that

- record the per-thread held-lock stack,
- build the global acquisition-order graph (edges between lock *classes*,
  keyed by creation site — two instances of ``ContinuousBatcher`` share
  one witness name, exactly like lockdep lock classes),
- flag **cycle formation** (A taken under B somewhere, B taken under A
  somewhere else = a potential deadlock, even if the two paths never
  raced yet) with both witness stacks,
- flag **blocking-while-holding**: entering a blocking boundary —
  ``queue.Queue.get``, an HTTP forward (``http.client``),
  ``subprocess`` waits, or a chaos ``HangUntilCancelled`` — while any
  witness lock is held. A lock held across an unbounded wait starves
  every sibling thread that needs it; the PR 9/10 review rounds caught
  two of these by hand, this catches them by machine,
- flag **waits-while-holding** Condition inversions: ``Condition.wait``
  releases *its own* lock, but any OTHER witness lock still held sleeps
  with the waiter.

Violations are recorded (never raised mid-flight — a witness must not
change the system it observes); the conftest guard fails the responsible
test, and ``analysis/lockdep_allow.toml`` is the explicit, reviewed
allowlist for the few accepted edges. See ``docs/static_analysis.md``.

Construction-site filtering keeps the blast radius zero for everything
else: a lock created from stdlib code (``queue``, ``logging``,
``concurrent.futures``) gets the real primitive, so only package locks
pay the (small, measured: ``bench.py --analysis`` bounds it at < 5% on
the serving hot path) bookkeeping cost.
"""

from __future__ import annotations

import linecache
import os
import re
import sys
import threading
import traceback
from typing import Dict, List, Optional, Tuple

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ALLOWLIST_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "lockdep_allow.toml")

# real primitives, captured before any patching can replace them
_real_lock = threading.Lock
_real_rlock = threading.RLock
_real_condition = threading.Condition


class Violation:
    """One witnessed discipline violation. ``key`` is the stable identity
    the allowlist matches on; ``stacks`` carries the witness stack(s)."""

    def __init__(self, kind: str, key: str, message: str,
                 stacks: Optional[List[str]] = None):
        self.kind = kind          # "cycle" | "blocking" | "wait-holding"
        self.key = key
        self.message = message
        self.stacks = stacks or []

    def render(self) -> str:
        out = [f"[{self.kind}] {self.key}", f"  {self.message}"]
        for i, s in enumerate(self.stacks):
            out.append(f"  --- witness stack {i + 1} ---")
            out.extend("  " + ln for ln in s.rstrip().split("\n"))
        return "\n".join(out)

    def to_dict(self) -> Dict[str, object]:
        return {"kind": self.kind, "key": self.key,
                "message": self.message, "stacks": self.stacks}


# --------------------------------------------------------------------------
# allowlist: a deliberately tiny TOML subset (this interpreter is 3.10,
# tomllib lands in 3.11). Supported: ``[[cycle]]`` / ``[[blocking]]`` /
# ``[[wait]]`` array-of-table headers with ``key = "string"`` entries.
def parse_allowlist(text: str) -> Dict[str, List[Dict[str, str]]]:
    sections: Dict[str, List[Dict[str, str]]] = {
        "cycle": [], "blocking": [], "wait": []}
    current: Optional[Dict[str, str]] = None
    for lineno, raw in enumerate(text.split("\n"), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        m = re.fullmatch(r"\[\[(\w+)\]\]", line)
        if m:
            name = m.group(1)
            if name not in sections:
                raise ValueError(
                    f"lockdep_allow.toml:{lineno}: unknown table {name!r}")
            current = {}
            sections[name].append(current)
            continue
        m = re.fullmatch(r'(\w+)\s*=\s*"((?:[^"\\]|\\.)*)"', line)
        if m and current is not None:
            current[m.group(1)] = m.group(2)
            continue
        raise ValueError(f"lockdep_allow.toml:{lineno}: unparseable line "
                         f"{line!r}")
    for name, rows in sections.items():
        for row in rows:
            if "reason" not in row:
                raise ValueError(f"lockdep_allow.toml: every [[{name}]] "
                                 f"entry needs a reason (got {row})")
    return sections


def _load_allowlist(path: str = _ALLOWLIST_PATH):
    try:
        with open(path) as f:
            return parse_allowlist(f.read())
    except FileNotFoundError:
        return {"cycle": [], "blocking": [], "wait": []}


# --------------------------------------------------------------------------
def _derive_name(frame) -> Optional[str]:
    """Name a lock from its construction site: module + class (via the
    frame's ``self``) + the assigned attribute parsed off the source line.
    Returns None for construction sites outside the package (those get
    real primitives). Names are line-number-free so the allowlist and the
    acquisition graph survive unrelated edits."""
    fn = frame.f_code.co_filename
    try:
        rel = os.path.relpath(fn, _PKG_ROOT)
    except ValueError:          # different drive (windows); not ours
        return None
    if rel.startswith("..") or not rel.endswith(".py"):
        return None
    mod = rel[:-3].replace(os.sep, ".")
    if mod.startswith("analysis."):
        return None             # the witness never witnesses itself
    if mod.endswith(".__init__"):
        mod = mod[:-len(".__init__")]
    line = linecache.getline(fn, frame.f_lineno)
    m = re.search(r"(?:self\.)?([A-Za-z_]\w*)\s*(?::[^=]+)?=[^=]", line)
    attr = m.group(1) if m else f"anon_L{frame.f_lineno}"
    slf = frame.f_locals.get("self")
    cls = type(slf).__name__ if slf is not None else None
    fn_name = frame.f_code.co_name
    if cls is not None:
        return f"{mod}.{cls}.{attr}"
    if fn_name != "<module>":
        return f"{mod}.{fn_name}.{attr}"
    return f"{mod}.{attr}"


def _site(frame) -> str:
    return f"{os.path.relpath(frame.f_code.co_filename, _PKG_ROOT)}" \
           f":{frame.f_lineno}"


_THIS_FILE = os.path.abspath(__file__)


def _caller_frame():
    """First frame outside this module (the user code acquiring)."""
    f = sys._getframe(1)
    while f is not None and f.f_code.co_filename == _THIS_FILE:
        f = f.f_back
    return f or sys._getframe(1)


def _capture_stack(limit: int = 18) -> str:
    try:
        return "".join(traceback.format_stack(_caller_frame(), limit=limit))
    except Exception:           # pragma: no cover - diagnostics only
        return "(stack unavailable)"


class Witness:
    """The acquisition-order graph plus the violation ledger. One global
    default instance backs the patched constructors; tests build their
    own isolated instances (``isolated()``) so fixture deadlocks don't
    contaminate the suite's graph."""

    def __init__(self, allowlist: Optional[dict] = None):
        self._mu = _real_lock()           # guards: _edges, _violations, _seen_keys, _lock_names
        self._tls = threading.local()
        # edge (a, b) -> (acquire-site, witness stack) of first observation
        self._edges: Dict[Tuple[str, str], Tuple[str, str]] = {}
        self._violations: List[Violation] = []
        self._seen_keys: set = set()
        self._lock_names: set = set()
        self._taken = 0                   # take_new_violations cursor
        self.allowlist = allowlist if allowlist is not None \
            else _load_allowlist()

    # ------------------------------------------------------------ factories
    def make_lock(self, name: str, site: str = "?") -> "_LockProxy":
        return _LockProxy(self, name, site)

    def make_rlock(self, name: str, site: str = "?") -> "_RLockProxy":
        return _RLockProxy(self, name, site)

    def make_condition(self, name: str, site: str = "?",
                       lock=None) -> "_ConditionProxy":
        return _ConditionProxy(self, name, site, lock)

    # ------------------------------------------------------------- held TLS
    def _held(self) -> list:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []
        return h

    def held_names(self) -> List[str]:
        return [p.name for p in self._held()]

    # ---------------------------------------------------------- allowlisting
    def _allowed(self, kind: str, **fields) -> bool:
        for row in self.allowlist.get(kind, ()):
            if all(row.get(k) == v for k, v in fields.items()):
                return True
        return False

    # ------------------------------------------------------------ recording
    def _record(self, v: Violation) -> None:
        with self._mu:
            if v.key in self._seen_keys:
                return
            self._seen_keys.add(v.key)
            self._violations.append(v)

    def violations(self) -> List[Violation]:
        with self._mu:
            return list(self._violations)

    def take_new_violations(self) -> List[Violation]:
        """Violations recorded since the last call — the per-test guard's
        read, so each failure is attributed to the test that induced it."""
        with self._mu:
            new = self._violations[self._taken:]
            self._taken = len(self._violations)
            return list(new)

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self._violations.clear()
            self._seen_keys.clear()
            self._taken = 0

    def stats(self) -> Dict[str, int]:
        with self._mu:
            return {"locks": len(self._lock_names),
                    "edges": len(self._edges),
                    "violations": len(self._violations)}

    # --------------------------------------------------------------- events
    def note_created(self, name: str) -> None:
        # lock-free: set.add is atomic in CPython, and this runs per
        # construction (per request for the batcher's _Request condition)
        self._lock_names.add(name)

    def before_acquire(self, proxy) -> None:
        """Called before a blocking acquire: adds the (top-of-stack ->
        proxy) edge and checks it for cycle formation. Top-only edges are
        enough — the rest of the held stack already has edges to the top,
        so any cycle through a deeper lock closes transitively."""
        held = self._held()
        if not held:
            return
        top = held[-1]
        a, b = top.name, proxy.name
        # known-edge fast path, deliberately outside _mu: _edges is
        # add-only and CPython dict reads are safe against concurrent
        # inserts, so the steady state (every edge already witnessed)
        # costs one dict probe and no global mutex
        if a != b and (a, b) in self._edges:
            return
        if a == b:
            # same lock class nested (two instances, or a real
            # self-deadlock on one instance). Either way it is an
            # ordering hazard between identically-named locks.
            key = f"cycle:{a} -> {b}"
            if not self._allowed("cycle", edge=f"{a} -> {b}"):
                self._record(Violation(
                    "cycle", key,
                    f"lock class {a!r} acquired while already held by this "
                    f"thread (self-order: instance nesting needs an "
                    f"explicit hierarchy)",
                    [_capture_stack()]))
            return
        with self._mu:
            known = (a, b) in self._edges
            if not known:
                self._edges[(a, b)] = (_site(_caller_frame()),
                                       _capture_stack())
                cycle_path = self._find_path(b, a)
            else:
                cycle_path = None
        if cycle_path is not None:
            edge_txt = f"{a} -> {b}"
            key = f"cycle:{edge_txt}"
            if not self._allowed("cycle", edge=edge_txt) \
                    and not self._allowed("cycle",
                                          edge=f"{b} -> {a}"):
                back = " -> ".join(cycle_path)
                with self._mu:
                    back_stack = self._edges.get(
                        (cycle_path[0], cycle_path[1]),
                        ("?", "(stack unavailable)"))[1]
                self._record(Violation(
                    "cycle", key,
                    f"lock-order cycle: this thread takes {edge_txt} while "
                    f"the graph already holds {back} — two threads on these "
                    f"paths can deadlock",
                    [_capture_stack(), back_stack]))

    def _find_path(self, src: str, dst: str) -> Optional[List[str]]:
        """DFS for src ~> dst over recorded edges; caller holds _mu."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for (x, y) in self._edges:
                if x == node and y not in seen:
                    seen.add(y)
                    stack.append((y, path + [y]))
        return None

    def note_acquired(self, proxy) -> None:
        self._held().append(proxy)

    def note_released(self, proxy) -> None:
        held = self._held()
        # normal case is LIFO; out-of-order release is legal Python, so
        # remove by identity wherever it sits
        for i in range(len(held) - 1, -1, -1):
            if held[i] is proxy:
                del held[i]
                return

    # ------------------------------------------------- blocking boundaries
    def check_blocking(self, op: str) -> None:
        """A blocking boundary (queue.get / HTTP / subprocess / chaos
        hang) is being entered; any held witness lock is a violation."""
        held = self._held()
        if not held:
            return
        top = held[-1]
        if self._allowed("blocking", lock=top.name, op=op):
            return
        key = f"blocking:{top.name} @ {op}"
        self._record(Violation(
            "blocking", key,
            f"blocking call {op!r} entered while holding {top.name!r} "
            f"(held stack: {self.held_names()}) — every thread needing "
            f"that lock now waits on this I/O",
            [_capture_stack()]))

    def check_wait(self, cond_proxy) -> None:
        """Condition.wait releases the condition's own lock; anything
        else still held sleeps with the waiter."""
        others = [p for p in self._held()
                  if p is not cond_proxy and p.name != cond_proxy.name]
        if not others:
            return
        top = others[-1]
        if self._allowed("wait", cond=cond_proxy.name, holding=top.name):
            return
        key = f"wait-holding:{cond_proxy.name} while {top.name}"
        self._record(Violation(
            "wait-holding", key,
            f"Condition {cond_proxy.name!r} waits while this thread still "
            f"holds {top.name!r} — the wait parks the lock until notify",
            [_capture_stack()]))


class _LockProxy:
    """threading.Lock stand-in with witness bookkeeping.

    The hot path is deliberately inlined: an uncontended ``with lock:``
    on an empty held stack costs one thread-local read, one list
    append/pop and two bound C-lock calls — measured ~3x a raw lock in
    nanoseconds, bounded < 5% end-to-end by ``bench.py --analysis``."""

    __slots__ = ("_real", "_witness", "_tls", "_racquire", "_rrelease",
                 "name", "site", "_owner")

    def __init__(self, witness: Witness, name: str, site: str):
        self._real = _real_lock()
        self._racquire = self._real.acquire
        self._rrelease = self._real.release
        self._witness = witness
        self._tls = witness._tls
        self.name = name
        self.site = site
        self._owner: Optional[int] = None
        witness.note_created(name)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        if blocking and held:
            self._witness.before_acquire(self)
        ok = self._racquire(blocking, timeout)
        if ok:
            self._owner = threading.get_ident()
            held.append(self)
        return ok

    def release(self) -> None:
        self._owner = None
        held = getattr(self._tls, "held", None)
        if held:
            if held[-1] is self:
                held.pop()
            else:               # out-of-order release (legal, rare)
                for i in range(len(held) - 1, -1, -1):
                    if held[i] is self:
                        del held[i]
                        break
        self._rrelease()

    def locked(self) -> bool:
        return self._real.locked()

    # Condition-compatibility (threading.Condition probes for these)
    def _is_owned(self) -> bool:
        return self._owner == threading.get_ident()

    def __enter__(self) -> bool:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        if held:
            self._witness.before_acquire(self)
        self._racquire()
        self._owner = threading.get_ident()
        held.append(self)
        return True

    def __exit__(self, *exc) -> None:
        self._owner = None
        held = self._tls.held
        if held[-1] is self:
            held.pop()
        else:
            for i in range(len(held) - 1, -1, -1):
                if held[i] is self:
                    del held[i]
                    break
        self._rrelease()

    def __repr__(self) -> str:
        return f"<lockdep Lock {self.name} @ {self.site}>"


class _RLockProxy:
    """threading.RLock stand-in: recursion tracked so the held stack and
    the order graph see only the outermost acquire/release."""

    __slots__ = ("_real", "_witness", "name", "site", "_owner", "_count")

    def __init__(self, witness: Witness, name: str, site: str):
        self._real = _real_rlock()
        self._witness = witness
        self.name = name
        self.site = site
        self._owner: Optional[int] = None
        self._count = 0
        witness.note_created(name)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = threading.get_ident()
        first = self._owner != me
        if blocking and first:
            self._witness.before_acquire(self)
        ok = self._real.acquire(blocking, timeout)
        if ok:
            if first:
                self._owner = me
                self._count = 1
                self._witness.note_acquired(self)
            else:
                self._count += 1
        return ok

    def release(self) -> None:
        if self._owner != threading.get_ident():
            raise RuntimeError("cannot release un-acquired lock")
        self._count -= 1
        if self._count == 0:
            self._owner = None
            self._witness.note_released(self)
        self._real.release()

    # Condition-compatibility trio
    def _is_owned(self) -> bool:
        return self._owner == threading.get_ident()

    def _release_save(self):
        count, owner = self._count, self._owner
        self._count = 0
        self._owner = None
        self._witness.note_released(self)
        for _ in range(count):
            self._real.release()
        return (count, owner)

    def _acquire_restore(self, state) -> None:
        count, owner = state
        self._witness.before_acquire(self)
        for _ in range(count):
            self._real.acquire()
        self._count = count
        self._owner = owner
        self._witness.note_acquired(self)

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<lockdep RLock {self.name} @ {self.site}>"


class _ConditionProxy:
    """threading.Condition stand-in whose wait() checks for other held
    witness locks (the waits-while-holding inversion).

    The underlying Condition and its RLock are REAL primitives — all the
    notify/wait release-restore machinery runs at C speed (a _Request
    constructs one of these per serving request). The proxy participates
    in the witness only at the edges: enter/exit maintain the held stack
    (so condition locks appear in the acquisition-order graph), and
    wait()/wait_for() run the waits-while-holding check."""

    __slots__ = ("_witness", "_tls", "name", "site", "_real")

    def __init__(self, witness: Witness, name: str, site: str, lock=None):
        self._witness = witness
        self._tls = witness._tls
        self.name = name
        self.site = site
        # an explicit lock may be a witness proxy (it quacks enough for
        # threading.Condition) or a real primitive; default is real
        self._real = _real_condition(lock)
        witness.note_created(name)

    # lock face: the held-stack entry IS this proxy
    def acquire(self, *a, **kw):
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        if held:
            self._witness.before_acquire(self)
        ok = self._real.acquire(*a, **kw)
        if ok:
            held.append(self)
        return ok

    def release(self):
        held = getattr(self._tls, "held", None)
        if held:
            for i in range(len(held) - 1, -1, -1):
                if held[i] is self:
                    del held[i]
                    break
        return self._real.release()

    def __enter__(self):
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        if held:
            self._witness.before_acquire(self)
        self._real.__enter__()
        held.append(self)
        return self

    def __exit__(self, *exc):
        held = self._tls.held
        if held[-1] is self:
            held.pop()
        else:
            for i in range(len(held) - 1, -1, -1):
                if held[i] is self:
                    del held[i]
                    break
        return self._real.__exit__(*exc)

    def wait(self, timeout: Optional[float] = None):
        held = getattr(self._tls, "held", None)
        if held and len(held) > 1:
            self._witness.check_wait(self)
        return self._real.wait(timeout)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        held = getattr(self._tls, "held", None)
        if held and len(held) > 1:
            self._witness.check_wait(self)
        return self._real.wait_for(predicate, timeout)

    def notify(self, n: int = 1):
        return self._real.notify(n)

    def notify_all(self):
        return self._real.notify_all()

    def __repr__(self) -> str:
        return f"<lockdep Condition {self.name} @ {self.site}>"


# --------------------------------------------------------------------------
# the global witness + constructor/boundary patching

_default_witness: Optional[Witness] = None
_patch_mu = _real_lock()                 # guards: _enabled, _originals
_enabled = False
_originals: Dict[str, object] = {}


def default_witness() -> Witness:
    global _default_witness
    if _default_witness is None:
        _default_witness = Witness()
    return _default_witness


class isolated:
    """``with lockdep.isolated() as w:`` — route the patched constructors
    and boundary checks to a fresh Witness for the scope, so analyzer
    self-tests can induce cycles without dirtying the suite's graph."""

    def __init__(self, allowlist: Optional[dict] = None):
        self.witness = Witness(allowlist=allowlist or
                               {"cycle": [], "blocking": [], "wait": []})

    def __enter__(self) -> Witness:
        global _default_witness
        self._prev = _default_witness
        _default_witness = self.witness
        return self.witness

    def __exit__(self, *exc) -> None:
        global _default_witness
        _default_witness = self._prev


# (filename, lineno) -> (name|None, site). A construction site's name is
# derived once — per-request lock constructions (each _Request carries a
# Condition) cost two dict probes, not a path walk.
_SITE_CACHE: Dict[Tuple[str, int], Tuple[Optional[str], str]] = {}


def _site_info(frame) -> Tuple[Optional[str], str]:
    key = (frame.f_code.co_filename, frame.f_lineno)
    hit = _SITE_CACHE.get(key)
    if hit is None:
        hit = (_derive_name(frame), _site(frame))
        _SITE_CACHE[key] = hit
    return hit


def _patched_lock():
    name, site = _site_info(sys._getframe(1))
    if name is None:
        return _real_lock()
    return default_witness().make_lock(name, site)


def _patched_rlock():
    name, site = _site_info(sys._getframe(1))
    if name is None:
        return _real_rlock()
    return default_witness().make_rlock(name, site)


def _patched_condition(lock=None):
    name, site = _site_info(sys._getframe(1))
    if name is None:
        return _real_condition(lock)
    return default_witness().make_condition(name, site, lock)


def enabled() -> bool:
    return _enabled


def enable() -> None:
    """Install the witness: patch the threading constructors (package
    construction sites only) and the blocking boundaries. Idempotent."""
    global _enabled
    with _patch_mu:
        if _enabled:
            return
        import http.client
        import queue
        import subprocess

        _originals["Lock"] = threading.Lock
        _originals["RLock"] = threading.RLock
        _originals["Condition"] = threading.Condition
        threading.Lock = _patched_lock
        threading.RLock = _patched_rlock
        threading.Condition = _patched_condition

        def _wrap_boundary(cls, attr, op, store):
            orig = getattr(cls, attr)
            _originals[store] = (cls, attr, orig)

            def wrapped(self, *a, **kw):
                w = _default_witness
                if (w is not None
                        and getattr(w._tls, "held", None)
                        and op_is_blocking(op, a, kw)):
                    w.check_blocking(op)
                return orig(self, *a, **kw)

            setattr(cls, attr, wrapped)

        def op_is_blocking(op, a, kw) -> bool:
            if op == "queue.get":
                # get(block=False) / get_nowait cannot park the holder
                return bool(a[0]) if a else bool(kw.get("block", True))
            return True

        _wrap_boundary(queue.Queue, "get", "queue.get", "queue_get")
        _wrap_boundary(http.client.HTTPConnection, "getresponse",
                       "http.request", "http_getresponse")
        _wrap_boundary(http.client.HTTPConnection, "connect",
                       "http.connect", "http_connect")
        _wrap_boundary(subprocess.Popen, "wait", "subprocess.wait",
                       "popen_wait")
        try:
            from deeplearning4j_tpu.runtime import chaos as _chaos
            _wrap_boundary(_chaos.HangUntilCancelled, "apply",
                           "chaos.hang", "chaos_hang")
        except Exception:       # pragma: no cover - import cycle guard
            pass
        _enabled = True


def disable() -> None:
    """Remove every patch (existing proxy locks keep working — they hold
    real primitives — but stop contributing new constructions)."""
    global _enabled
    with _patch_mu:
        if not _enabled:
            return
        threading.Lock = _originals.pop("Lock")
        threading.RLock = _originals.pop("RLock")
        threading.Condition = _originals.pop("Condition")
        for key in list(_originals):
            cls, attr, orig = _originals.pop(key)
            setattr(cls, attr, orig)
        _enabled = False


def enable_from_env() -> bool:
    """The production opt-in: ``DL4J_TPU_LOCKDEP=1`` in the environment
    enables the witness at import (fleet worker subprocesses inherit the
    env, so a drill's whole process tree is witnessed)."""
    if os.environ.get("DL4J_TPU_LOCKDEP", "") == "1":
        enable()
        return True
    return False


def violations() -> List[Violation]:
    return default_witness().violations()


def take_new_violations() -> List[Violation]:
    return default_witness().take_new_violations()


def render_report(vs: List[Violation]) -> str:
    if not vs:
        return "lockdep: no violations"
    return "\n\n".join(v.render() for v in vs)
