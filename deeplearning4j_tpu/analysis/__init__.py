"""Static + runtime concurrency analysis (ISSUE 14).

Two halves, one contract — the fleet's thread discipline is machine
checked, not reviewed by hand:

- :mod:`deeplearning4j_tpu.analysis.lockdep` — the runtime lock-order
  witness (``DL4J_TPU_LOCKDEP=1``): named lock proxies, the acquisition-
  order graph, cycle / blocking-while-holding / waits-while-holding
  detection, ``lockdep_allow.toml`` as the reviewed allowlist.
- :mod:`deeplearning4j_tpu.analysis.lint` — the AST project-invariant
  linter (``python -m deeplearning4j_tpu.analysis``): thread naming,
  ``# guards:`` lock declarations, chaos-point registry/doc/test parity,
  route + metric documentation, wallclock bans in trajectory modules.

The registries both halves (and conftest) share live in
:mod:`deeplearning4j_tpu.analysis.registry`. The playbook is
``docs/static_analysis.md``; this package plays the role TSan/sanitizer
builds play for libnd4j in the reference (``docs/parity.md``).
"""

from deeplearning4j_tpu.analysis import lockdep, registry  # noqa: F401
