"""Single source of truth for the project's concurrency/observability
registries — the names the AST linter (``analysis/lint.py``) enforces and
the tier-1 conftest consumes.

Keeping these HERE (not in conftest, not scattered per-module) is the
point of ISSUE 14's last satellite: conftest's ``_PIPELINE_THREAD_NAMES``
imports :data:`PIPELINE_THREAD_NAMES`, and the lint checks every
``threading.Thread(name=...)`` in the package against
:data:`THREAD_NAME_PREFIXES` — the two can never drift because there is
only one tuple of each.

Dependency rule: this module must stay stdlib-free-of-imports (conftest
and ``python -m deeplearning4j_tpu.analysis`` both load it before jax is
configured in some flows).
"""

# Background threads every fit()/close()/stop()/aggregate path must JOIN —
# the conftest leak guard fails any test one of these survives. A name
# goes here only when some shutdown path owns joining it.
PIPELINE_THREAD_NAMES = (
    "train-prefetch",
    "train-listener-delivery",
    "async-dataset-iterator",
    "trace-collector",
    "slo-autoscaler",
    "lease-election",
    "session-evictor",          # SessionStore idle-TTL/byte-budget sweeper
    "stream-writer",            # per-stream SSE writer (joined by handler)
    "fleet-scheduler",          # background-job control tick + "-job" runner
)

# Every thread the package spawns must carry a name starting with one of
# these prefixes (the lint resolves the static prefix of each
# ``threading.Thread(name=...)`` call). An unlisted prefix is a finding:
# register it here — deliberately, in review — or rename the thread.
THREAD_NAME_PREFIXES = PIPELINE_THREAD_NAMES + (
    "ContinuousBatcher",        # batcher coalescer + "-complete" stage
    "ModelServer",              # serving HTTP front end
    "FleetRouter",              # router HTTP server + "-probe" loop
    "FleetSupervisor",          # worker-process watchdog
    "FaultTolerantTrainer-epoch",
    "router-forward",           # per-attempt forward threads (joined by race)
    "ui-stats-server",          # ui/server.py stats HTTP thread
    "dist-exchange",            # overlapped gradient allgather (ISSUE 20,
                                # joined by DistributedTrainer.close)
)

# Prometheus metric-name namespaces the package may emit. The lint
# recognises a metric emission by shape (``name{labels} value`` /
# ``# TYPE name``), then requires (a) the name to live in one of these
# namespaces and (b) the name to be documented in docs/observability.md.
METRIC_NAMESPACES = (
    "serving_",
    "router_",
    "fleet_",
    "capacity_",
    "compile_cache_",
    "config_",
    "slo_",
    "trace_",
    "autoscaler_",
    "registry_",
    "paging_",
    "aot_",                     # AOT dispatch fast-path ledger (ISSUE 5)
    "journal_",                 # event-journal ring health (ISSUE 15)
    "incident_",                # anomaly-watchdog incidents (ISSUE 15)
    "scheduler_",               # background-job scheduler (ISSUE 19)
)

# Package directories whose code affects numeric trajectories — the
# bit-identity guarantee's blind spot. ``time.time()`` / ``time.time_ns``
# and the stdlib ``random`` module are banned here (inject a clock/RNG
# instead); observability timing uses ``time.monotonic`` /
# ``time.perf_counter``, which stay legal.
TRAJECTORY_MODULES = (
    "models",
    "nn",
    "ops",
    "autodiff",
    "parallel",
    "native",
    "train",
    "data",
)
