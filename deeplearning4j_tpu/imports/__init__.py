"""Model import.

Rebuild of the reference's import stack:

- ``TFGraphMapper`` (upstream ``org.nd4j.imports.graphmapper.tf``): frozen TF
  GraphDef protobuf → declarative graph. Parsing uses the local tensorflow
  (CPU) wheel as the protobuf/tensor decoder; execution is entirely this
  framework's (SameDiff-equivalent → XLA).
- ``KerasModelImport`` (upstream ``org.deeplearning4j.nn.modelimport.keras``):
  Keras H5/SavedModel → MultiLayerNetwork / ComputationGraph with weights.
- ``OnnxGraphMapper`` (upstream ``org.nd4j.imports.graphmapper.onnx``,
  partial there): ONNX ModelProto → declarative graph, via an in-repo
  protobuf wire decoder (no onnx package offline).
"""

from deeplearning4j_tpu.imports.tf_import import TFGraphMapper
from deeplearning4j_tpu.imports.keras_import import KerasModelImport
from deeplearning4j_tpu.imports.onnx_import import OnnxGraphMapper

__all__ = ["TFGraphMapper", "KerasModelImport", "OnnxGraphMapper"]
