"""Reference-style TF model builders used as import oracles.

The flagship declarative workflow of the reference is
``TFGraphMapper.importGraph(bert_frozen.pb)`` → graft a loss → ``sd.fit()``
(upstream ``org.nd4j.imports.graphmapper.tf.TFGraphMapper``; SURVEY.md §3.3,
BASELINE config #4). No pretrained checkpoint is downloadable in this
environment, so we construct the *same computation* — a faithful BERT
encoder GraphDef — with the local TensorFlow and deterministic random
weights. The oracle property is exact: whatever TF computes for this graph,
the imported SameDiff must reproduce.

Everything here runs TF on CPU only; the imported graph runs on TPU.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np


def build_bert_graphdef(
    batch: int = 2,
    seq_len: int = 128,
    hidden: int = 768,
    layers: int = 12,
    heads: int = 12,
    intermediate: int = 3072,
    vocab: int = 30522,
    type_vocab: int = 2,
    seed: int = 0,
) -> Tuple[object, List[str], List[str], Dict[str, np.ndarray]]:
    """Build a frozen BERT encoder GraphDef (original google-research/bert
    architecture: post-LN, gelu-via-erf, additive attention mask, tanh
    pooler on [CLS]).

    Returns ``(graph_def, input_names, output_names, weights)`` where
    ``weights`` maps logical parameter names to the numpy arrays baked into
    the graph (useful for asserting the importer picked them up).
    """
    import tensorflow as tf

    rng = np.random.default_rng(seed)
    dk = hidden // heads
    W: Dict[str, np.ndarray] = {}

    def mk(name, shape, scale=0.02):
        W[name] = rng.normal(0.0, scale, shape).astype(np.float32)
        return W[name]

    mk("word_emb", (vocab, hidden))
    mk("pos_emb", (seq_len, hidden))
    mk("type_emb", (type_vocab, hidden))
    W["emb_ln_g"] = np.ones(hidden, np.float32)
    W["emb_ln_b"] = np.zeros(hidden, np.float32)
    for i in range(layers):
        for nm, shape in (("q", (hidden, hidden)), ("k", (hidden, hidden)),
                          ("v", (hidden, hidden)), ("ao", (hidden, hidden)),
                          ("ff1", (hidden, intermediate)),
                          ("ff2", (intermediate, hidden))):
            mk(f"l{i}_{nm}_w", shape)
            W[f"l{i}_{nm}_b"] = np.zeros(shape[1], np.float32)
        for nm in ("attn_ln", "out_ln"):
            W[f"l{i}_{nm}_g"] = np.ones(hidden, np.float32)
            W[f"l{i}_{nm}_b"] = np.zeros(hidden, np.float32)
    mk("pool_w", (hidden, hidden))
    W["pool_b"] = np.zeros(hidden, np.float32)

    C = {k: tf.constant(v) for k, v in W.items()}

    def layer_norm(x, g, b):
        mean = tf.reduce_mean(x, axis=-1, keepdims=True)
        var = tf.reduce_mean(tf.math.squared_difference(x, mean), axis=-1,
                             keepdims=True)
        return (x - mean) * tf.math.rsqrt(var + 1e-12) * g + b

    def gelu(x):  # BERT's erf formulation
        return 0.5 * x * (1.0 + tf.math.erf(x / np.float32(np.sqrt(2.0))))

    def encoder(input_ids, token_type_ids, input_mask):
        x = (tf.gather(C["word_emb"], input_ids)
             + C["pos_emb"]
             + tf.gather(C["type_emb"], token_type_ids))
        x = layer_norm(x, C["emb_ln_g"], C["emb_ln_b"])
        # additive mask: (B, 1, 1, T), 0 for keep / -10000 for pad
        adder = (1.0 - tf.cast(input_mask, tf.float32)) * -10000.0
        adder = tf.reshape(adder, (batch, 1, 1, seq_len))
        for i in range(layers):
            def proj(nm):
                h = tf.matmul(tf.reshape(x, (batch * seq_len, hidden)),
                              C[f"l{i}_{nm}_w"]) + C[f"l{i}_{nm}_b"]
                h = tf.reshape(h, (batch, seq_len, heads, dk))
                return tf.transpose(h, (0, 2, 1, 3))

            q, k, v = proj("q"), proj("k"), proj("v")
            s = tf.matmul(q, k, transpose_b=True) / np.float32(np.sqrt(dk))
            p = tf.nn.softmax(s + adder, axis=-1)
            ctx = tf.matmul(p, v)
            ctx = tf.reshape(tf.transpose(ctx, (0, 2, 1, 3)),
                             (batch * seq_len, hidden))
            a = tf.matmul(ctx, C[f"l{i}_ao_w"]) + C[f"l{i}_ao_b"]
            x = layer_norm(tf.reshape(a, (batch, seq_len, hidden)) + x,
                           C[f"l{i}_attn_ln_g"], C[f"l{i}_attn_ln_b"])
            h = gelu(tf.matmul(tf.reshape(x, (batch * seq_len, hidden)),
                               C[f"l{i}_ff1_w"]) + C[f"l{i}_ff1_b"])
            h = tf.matmul(h, C[f"l{i}_ff2_w"]) + C[f"l{i}_ff2_b"]
            x = layer_norm(tf.reshape(h, (batch, seq_len, hidden)) + x,
                           C[f"l{i}_out_ln_g"], C[f"l{i}_out_ln_b"])
        seq_out = tf.identity(x, name="sequence_output")
        cls = x[:, 0, :]
        pooled = tf.tanh(tf.matmul(cls, C["pool_w"]) + C["pool_b"])
        pooled = tf.identity(pooled, name="pooled_output")
        return seq_out, pooled

    conc = tf.function(encoder).get_concrete_function(
        tf.TensorSpec((batch, seq_len), tf.int32, name="input_ids"),
        tf.TensorSpec((batch, seq_len), tf.int32, name="token_type_ids"),
        tf.TensorSpec((batch, seq_len), tf.int32, name="input_mask"),
    )
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)
    frozen = convert_variables_to_constants_v2(conc)
    gd = frozen.graph.as_graph_def()
    inputs = [t.name.split(":")[0] for t in frozen.inputs]
    outputs = [t.name.split(":")[0] for t in frozen.outputs]
    return gd, inputs, outputs, W


def bert_synthetic_batch(batch, seq_len, vocab, n_classes=2, seed=0):
    """SST-2-shaped synthetic batch: ids, types, mask (ragged lengths),
    one-hot labels."""
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, vocab, (batch, seq_len)).astype(np.int32)
    types = np.zeros((batch, seq_len), np.int32)
    lens = rng.integers(seq_len // 2, seq_len + 1, batch)
    mask = (np.arange(seq_len)[None, :] < lens[:, None]).astype(np.int32)
    labels = np.eye(n_classes, dtype=np.float32)[rng.integers(0, n_classes, batch)]
    return ids, types, mask, labels


def graft_classifier(sd, pooled_name: str, hidden: int, n_classes: int = 2,
                     seed: int = 0):
    """Graft a classification head + loss onto an imported encoder (the
    reference fine-tune recipe: importGraph → add head vars → sd.fit).
    Returns (logits_var, loss_var); adds placeholder ``labels``."""
    rng = np.random.default_rng(seed)
    w = sd.var("cls_w", array=rng.normal(0, 0.02, (hidden, n_classes)).astype(np.float32))
    b = sd.var("cls_b", array=np.zeros(n_classes, np.float32))
    pooled = sd.vars[pooled_name]
    logits = sd.invoke("linear", pooled, w, b, name="cls_logits")
    labels = sd.placeholder("labels", (None, n_classes))
    loss = sd.loss.softmax_cross_entropy("finetune_loss", labels, logits)
    return logits, loss
