"""Keras model import.

Rebuild of upstream ``org.deeplearning4j.nn.modelimport.keras.KerasModelImport``:
``.h5`` / ``.keras`` archives → ``MultiLayerNetwork`` (Sequential) or
``ComputationGraph`` (Functional), with weights copied in. The local
tensorflow wheel is the HDF5/JSON decoder (the reference used JavaCPP hdf5);
everything downstream is native to this framework.

Layer coverage mirrors the reference's mappers: Dense, Conv2D/1D,
SeparableConv2D, MaxPooling/AveragePooling, GlobalAvg/MaxPooling,
BatchNormalization, Dropout, Flatten, Activation/ReLU/Softmax, Embedding,
LSTM/GRU/SimpleRNN (+ Bidirectional), ZeroPadding2D, UpSampling2D, and
Add/Concatenate merge nodes on the functional path.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.nn import (
    ActivationLayer, BatchNormalization, Bidirectional, ConvolutionLayer,
    Deconvolution2D, DenseLayer, DropoutLayer, EmbeddingSequenceLayer, GRU,
    GlobalPoolingLayer, InputType, LSTM, NeuralNetConfiguration, OutputLayer,
    PoolingType, SeparableConvolution2D, SimpleRnn, SubsamplingLayer,
    Upsampling2D, ZeroPaddingLayer)
from deeplearning4j_tpu.nn.preprocessors import CnnToFeedForwardPreProcessor


# Custom-layer SPI (reference ``KerasLayer.registerCustomLayer``): maps a
# Keras class name to a factory ``(keras_layer, config_dict) -> Layer``.
_CUSTOM_LAYER_REGISTRY: Dict[str, object] = {}


def register_custom_layer(keras_class_name: str, factory) -> None:
    """Register a mapper for a custom Keras layer class. ``factory`` is
    called with ``(keras_layer, get_config() dict)`` and returns one of our
    layer configs (or None for a structural no-op)."""
    _CUSTOM_LAYER_REGISTRY[keras_class_name] = factory


def register_lambda_layer(name: str, fn) -> None:
    """Reference ``KerasLayer.registerLambdaLayer``: Keras never serializes
    Lambda code, so imports resolve Lambda layers BY NAME from this registry
    (``fn`` is any jax-traceable ``x -> y``)."""
    from deeplearning4j_tpu.nn.misc_layers import register_lambda
    register_lambda(name, fn)


def _archive_lambda_names(path: str) -> List[str]:
    """Names of every Lambda layer in a ``.keras``/``.h5`` archive, read from
    the config JSON WITHOUT deserializing any layer (no code can run)."""
    import json
    import zipfile

    def walk(node, out):
        if isinstance(node, dict):
            if node.get("class_name") == "Lambda":
                out.append(node.get("config", {}).get("name", ""))
            for v in node.values():
                walk(v, out)
        elif isinstance(node, list):
            for v in node:
                walk(v, out)

    names: List[str] = []
    if zipfile.is_zipfile(path):
        with zipfile.ZipFile(path) as z:
            cfg = json.loads(z.read("config.json"))
        walk(cfg, names)
    else:  # legacy HDF5: model_config attr
        import h5py  # bundled with tensorflow
        with h5py.File(path, "r") as f:
            raw = f.attrs.get("model_config")
            if raw is not None:
                if isinstance(raw, bytes):
                    raw = raw.decode()
                walk(json.loads(raw), names)
    return names


def _make_subst_lambda():
    """A stand-in deserialization target for Keras ``Lambda``: it keeps the
    layer's config (name, output_shape) and NEVER deserializes the archive's
    marshaled lambda bytecode — Keras safe mode therefore stays ON and no
    archive-controlled code can run. ``_map_layer`` later substitutes the
    function the user registered under the layer's name."""
    import tensorflow as tf

    class _SubstLambda(tf.keras.layers.Layer):
        def __init__(self, dl4j_cfg=None, **kw):
            kw.pop("function", None)
            kw.pop("output_shape", None)
            kw.pop("arguments", None)
            super().__init__(name=(dl4j_cfg or {}).get("name"))
            self._dl4j_cfg = dl4j_cfg or {}

        @classmethod
        def from_config(cls, config, custom_objects=None):
            return cls(dl4j_cfg=config)

        def build(self, input_shape):
            self.built = True

        def _static_out_shape(self):
            """The archive's declared output_shape (per-sample, no batch dim)
            when it is a plain int sequence — shape-changing Lambdas must
            declare it for the downstream layers to rebuild correctly."""
            s = self._dl4j_cfg.get("output_shape")
            if (isinstance(s, (list, tuple)) and s
                    and all(isinstance(v, int) for v in s)):
                return tuple(s)
            return None

        def call(self, x):  # structural placeholder; never the real fn
            s = self._static_out_shape()
            if s is None:
                return x
            import tensorflow as _tf
            batch = _tf.shape(x)[0]
            return _tf.zeros(_tf.concat([[batch], list(s)], axis=0),
                             dtype=x.dtype)

        def compute_output_shape(self, input_shape):
            s = self._static_out_shape()
            if s is None:
                return input_shape
            return (input_shape[0],) + s

    return _SubstLambda


class KerasModelImport:
    @staticmethod
    def import_keras_model_and_weights(path: str):
        """Returns a MultiLayerNetwork (Sequential) or ComputationGraph.
        Keras 2/3 archives load through tf.keras; Keras 1.x H5 files (which
        modern Keras refuses) go through the legacy dialect parser."""
        if _is_keras1_h5(path):
            return _import_keras1_h5(path)
        import tensorflow as tf
        from deeplearning4j_tpu.nn.misc_layers import _LAMBDA_REGISTRY
        lambda_names = _archive_lambda_names(path)
        if lambda_names:
            missing = [n for n in lambda_names if n not in _LAMBDA_REGISTRY]
            if missing:
                raise NotImplementedError(
                    f"model contains Keras Lambda layers {missing} without "
                    f"registered functions; call "
                    f"KerasModelImport.register_lambda_layer(name, fn) for "
                    f"each before import")
            # Swap the Lambda deserializer for a stand-in that ignores the
            # archive's marshaled code entirely (safe mode stays ON; the
            # registered functions are what run). Scoped patch: Keras ignores
            # custom_objects for its own module path, so from_config is
            # replaced for the duration of this load only.
            lam_cls = tf.keras.layers.Lambda
            subst = _make_subst_lambda()
            orig_from_config = lam_cls.from_config.__func__
            lam_cls.from_config = classmethod(
                lambda cls, config, **kw: subst(dl4j_cfg=config))
            try:
                km = tf.keras.models.load_model(path, compile=False)
            finally:
                lam_cls.from_config = classmethod(orig_from_config)
        else:
            km = tf.keras.models.load_model(path, compile=False)
        if isinstance(km, tf.keras.Sequential):
            return _import_sequential(km)
        return _import_functional(km)

    # reference aliases
    import_keras_sequential_model_and_weights = import_keras_model_and_weights
    import_keras_model = import_keras_model_and_weights
    register_custom_layer = staticmethod(register_custom_layer)
    register_lambda_layer = staticmethod(register_lambda_layer)


def _act_name(act) -> str:
    name = getattr(act, "__name__", str(act))
    return {"linear": "identity"}.get(name, name)


def _pair(v):
    return tuple(v) if isinstance(v, (tuple, list)) else (v, v)


def _map_layer(kl) -> Optional[object]:
    """Keras layer -> our layer config (None = structural no-op)."""
    import tensorflow as tf
    cls = type(kl).__name__
    cfg = kl.get_config()
    if cls in _CUSTOM_LAYER_REGISTRY:
        return _CUSTOM_LAYER_REGISTRY[cls](kl, cfg)
    if cls == "Lambda" or hasattr(kl, "_dl4j_cfg"):  # _SubstLambda stand-in
        from deeplearning4j_tpu.nn.misc_layers import LambdaLayer, get_lambda
        cfg = getattr(kl, "_dl4j_cfg", None) or cfg
        name = cfg.get("name", "")
        try:
            fn = get_lambda(name)
        except KeyError as e:
            raise NotImplementedError(
                f"Keras Lambda layer {name!r} has no registered function; "
                f"call KerasModelImport.register_lambda_layer({name!r}, fn) "
                f"before import") from e
        out_shape = cfg.get("output_shape")
        # output_shape may be a callable serialized as a dict (or a legacy
        # tuple of function parts) — only trust a plain int sequence.
        out_size = (out_shape[-1]
                    if isinstance(out_shape, (list, tuple)) and out_shape
                    and isinstance(out_shape[-1], int) else None)
        return LambdaLayer(fn=fn, fn_name=name, out_size=out_size)
    if cls == "Dense":
        return DenseLayer(n_out=cfg["units"], activation=_act_name(kl.activation),
                          has_bias=cfg.get("use_bias", True))
    if cls == "Conv2D":
        return ConvolutionLayer(
            n_out=cfg["filters"], kernel_size=_pair(cfg["kernel_size"]),
            stride=_pair(cfg["strides"]),
            convolution_mode="same" if cfg["padding"] == "same" else "truncate",
            dilation=_pair(cfg.get("dilation_rate", 1)),
            activation=_act_name(kl.activation), has_bias=cfg.get("use_bias", True))
    if cls == "SeparableConv2D":
        return SeparableConvolution2D(
            n_out=cfg["filters"], kernel_size=_pair(cfg["kernel_size"]),
            stride=_pair(cfg["strides"]),
            convolution_mode="same" if cfg["padding"] == "same" else "truncate",
            depth_multiplier=cfg.get("depth_multiplier", 1),
            activation=_act_name(kl.activation), has_bias=cfg.get("use_bias", True))
    if cls == "Conv2DTranspose":
        return Deconvolution2D(
            n_out=cfg["filters"], kernel_size=_pair(cfg["kernel_size"]),
            stride=_pair(cfg["strides"]),
            convolution_mode="same" if cfg["padding"] == "same" else "truncate",
            activation=_act_name(kl.activation), has_bias=cfg.get("use_bias", True))
    if cls == "MaxPooling2D":
        return SubsamplingLayer(pooling_type=PoolingType.MAX,
                                kernel_size=_pair(cfg["pool_size"]),
                                stride=_pair(cfg["strides"] or cfg["pool_size"]),
                                convolution_mode="same" if cfg["padding"] == "same" else "truncate")
    if cls == "AveragePooling2D":
        return SubsamplingLayer(pooling_type=PoolingType.AVG,
                                kernel_size=_pair(cfg["pool_size"]),
                                stride=_pair(cfg["strides"] or cfg["pool_size"]),
                                convolution_mode="same" if cfg["padding"] == "same" else "truncate")
    if cls in ("GlobalAveragePooling2D", "GlobalAveragePooling1D"):
        return GlobalPoolingLayer(pooling_type=PoolingType.AVG)
    if cls in ("GlobalMaxPooling2D", "GlobalMaxPooling1D"):
        return GlobalPoolingLayer(pooling_type=PoolingType.MAX)
    if cls == "BatchNormalization":
        return BatchNormalization(decay=cfg.get("momentum", 0.99),
                                  eps=cfg.get("epsilon", 1e-3))
    if cls == "Dropout":
        return DropoutLayer(dropout=1.0 - cfg["rate"])  # keras rate = drop prob
    if cls == "Activation":
        return ActivationLayer(activation=_act_name(kl.activation))
    if cls == "ReLU":
        return ActivationLayer(activation="relu")
    if cls == "Softmax":
        return ActivationLayer(activation="softmax")
    if cls == "LeakyReLU":
        return ActivationLayer(activation="leakyrelu")
    if cls == "Embedding":
        return EmbeddingSequenceLayer(n_in=cfg["input_dim"], n_out=cfg["output_dim"])
    if cls == "LSTM":
        return LSTM(n_out=cfg["units"], activation=_act_name(kl.activation),
                    gate_activation=_act_name(kl.recurrent_activation))
    if cls == "GRU":
        return GRU(n_out=cfg["units"],
                   reset_after=cfg.get("reset_after", True),
                   activation=_act_name(kl.activation),
                   gate_activation=_act_name(kl.recurrent_activation))
    if cls == "SimpleRNN":
        return SimpleRnn(n_out=cfg["units"], activation=_act_name(kl.activation))
    if cls == "Bidirectional":
        # keras 3 exposes forward_layer/backward_layer; keras 2 had .layer
        inner_k = getattr(kl, "layer", None) or kl.forward_layer
        inner = _map_layer(inner_k)
        mode = {"concat": "concat", "sum": "add", "ave": "average", "mul": "mul"}[
            cfg.get("merge_mode", "concat")]
        return Bidirectional(layer=inner, mode=mode)
    if cls == "ZeroPadding2D":
        return ZeroPaddingLayer(padding=cfg["padding"])
    if cls == "UpSampling2D":
        return Upsampling2D(size=_pair(cfg["size"]))
    if cls == "Conv1D":
        from deeplearning4j_tpu.nn import Convolution1DLayer
        mode = {"same": "same", "causal": "causal", "valid": "truncate"}[cfg["padding"]]
        return Convolution1DLayer(
            n_out=cfg["filters"], kernel_size=cfg["kernel_size"][0],
            stride=cfg["strides"][0], convolution_mode=mode,
            dilation=cfg.get("dilation_rate", [1])[0],
            activation=_act_name(kl.activation), has_bias=cfg.get("use_bias", True))
    if cls == "Conv3D":
        from deeplearning4j_tpu.nn import Convolution3D
        return Convolution3D(
            n_out=cfg["filters"], kernel_size=tuple(cfg["kernel_size"]),
            stride=tuple(cfg["strides"]),
            convolution_mode="same" if cfg["padding"] == "same" else "truncate",
            activation=_act_name(kl.activation), has_bias=cfg.get("use_bias", True))
    if cls in ("MaxPooling3D", "AveragePooling3D"):
        from deeplearning4j_tpu.nn import Subsampling3DLayer
        if cfg["padding"] == "same":
            raise NotImplementedError("MaxPooling3D padding='same' not supported")
        return Subsampling3DLayer(
            pooling_type="max" if cls.startswith("Max") else "avg",
            kernel_size=tuple(cfg["pool_size"]),
            stride=tuple(cfg["strides"] or cfg["pool_size"]))
    if cls == "Cropping1D":
        from deeplearning4j_tpu.nn import Cropping1D
        c = cfg["cropping"]
        c = (c, c) if isinstance(c, int) else tuple(c)
        return Cropping1D(crop_left=c[0], crop_right=c[1])
    if cls == "Cropping2D":
        from deeplearning4j_tpu.nn import Cropping2D
        cr = cfg["cropping"]
        return Cropping2D(crop=cr)
    if cls == "ZeroPadding1D":
        from deeplearning4j_tpu.nn import ZeroPadding1DLayer
        p = cfg["padding"]
        p = (p, p) if isinstance(p, int) else tuple(p)
        return ZeroPadding1DLayer(pad_left=p[0], pad_right=p[1])
    if cls == "UpSampling1D":
        from deeplearning4j_tpu.nn import Upsampling1D
        return Upsampling1D(size=cfg["size"])
    if cls == "UpSampling3D":
        from deeplearning4j_tpu.nn import Upsampling3D
        return Upsampling3D(size=tuple(cfg["size"]))
    if cls == "PReLU":
        from deeplearning4j_tpu.nn import PReLULayer
        shared = cfg.get("shared_axes") or ()
        return PReLULayer(shared_axes=tuple(shared))
    if cls == "ELU":
        return ActivationLayer(activation="elu")
    if cls == "RepeatVector":
        from deeplearning4j_tpu.nn import RepeatVector
        return RepeatVector(n=cfg["n"])
    if cls == "TimeDistributed":
        from deeplearning4j_tpu.nn import TimeDistributed
        return TimeDistributed(underlying=_map_layer(kl.layer))
    if cls == "SeparableConv1D":
        from deeplearning4j_tpu.nn import SeparableConvolution1D
        return SeparableConvolution1D(
            n_out=cfg["filters"],
            kernel_size=cfg["kernel_size"][0] if isinstance(cfg["kernel_size"], (tuple, list)) else cfg["kernel_size"],
            stride=cfg["strides"][0] if isinstance(cfg["strides"], (tuple, list)) else cfg["strides"],
            convolution_mode="same" if cfg["padding"] == "same" else "truncate",
            depth_multiplier=cfg.get("depth_multiplier", 1),
            activation=_act_name(kl.activation), has_bias=cfg.get("use_bias", True))
    if cls == "LocallyConnected1D":
        from deeplearning4j_tpu.nn import LocallyConnected1D
        return LocallyConnected1D(
            n_out=cfg["filters"],
            kernel_size=cfg["kernel_size"][0] if isinstance(cfg["kernel_size"], (tuple, list)) else cfg["kernel_size"],
            stride=cfg["strides"][0] if isinstance(cfg["strides"], (tuple, list)) else cfg["strides"],
            activation=_act_name(kl.activation), has_bias=cfg.get("use_bias", True))
    if cls == "LocallyConnected2D":
        from deeplearning4j_tpu.nn import LocallyConnected2D
        return LocallyConnected2D(
            n_out=cfg["filters"], kernel_size=_pair(cfg["kernel_size"]),
            stride=_pair(cfg["strides"]),
            activation=_act_name(kl.activation), has_bias=cfg.get("use_bias", True))
    if cls == "ConvLSTM2D":
        from deeplearning4j_tpu.nn import ConvLSTM2D
        if cfg.get("recurrent_activation", "sigmoid") not in ("sigmoid",):
            raise NotImplementedError(
                "ConvLSTM2D recurrent_activation "
                f"{cfg['recurrent_activation']!r} not mapped (sigmoid only)")
        if cfg.get("activation", "tanh") not in ("tanh",):
            raise NotImplementedError(
                f"ConvLSTM2D activation {cfg['activation']!r} not mapped")
        if cfg.get("dilation_rate") not in (None, 1, (1, 1), [1, 1]):
            raise NotImplementedError("ConvLSTM2D dilation not mapped")
        return ConvLSTM2D(n_out=cfg["filters"],
                          kernel_size=_pair(cfg["kernel_size"]),
                          stride=_pair(cfg.get("strides", 1)),
                          convolution_mode="same" if cfg["padding"] == "same"
                          else "truncate",
                          has_bias=cfg.get("use_bias", True),
                          return_sequences=cfg.get("return_sequences", False))
    if cls in ("MaxPooling1D", "AveragePooling1D"):
        from deeplearning4j_tpu.nn import Subsampling1DLayer
        ps = cfg["pool_size"]
        ps = ps[0] if isinstance(ps, (tuple, list)) else ps
        st = cfg["strides"] or ps
        st = st[0] if isinstance(st, (tuple, list)) else st
        return Subsampling1DLayer(
            pooling_type="max" if cls.startswith("Max") else "avg",
            kernel_size=ps, stride=st,
            convolution_mode="same" if cfg["padding"] == "same" else "truncate")
    if cls == "Permute":
        from deeplearning4j_tpu.nn import PermuteLayer
        return PermuteLayer(dims=tuple(cfg["dims"]))
    if cls == "ThresholdedReLU":
        from deeplearning4j_tpu.nn.misc_layers import LambdaLayer
        theta = float(cfg.get("theta", 1.0))
        import jax.numpy as _jnp
        return LambdaLayer(fn=lambda t, _th=theta: t * (t > _th).astype(t.dtype),
                           fn_name=f"thresholded_relu_{theta}")
    if cls in ("GlobalAveragePooling3D", "GlobalMaxPooling3D"):
        return GlobalPoolingLayer(
            pooling_type=PoolingType.AVG if "Average" in cls else PoolingType.MAX)
    if cls in ("SpatialDropout1D", "SpatialDropout2D", "GaussianDropout",
               "AlphaDropout"):
        # train-time-only stochastic layers; retain-prob dropout is the
        # closest training analog and all are identity at inference
        return DropoutLayer(dropout=1.0 - cfg.get("rate", 0.0))
    if cls == "Flatten":
        from deeplearning4j_tpu.nn import FlattenLayer
        return FlattenLayer()
    if cls in ("InputLayer", "Reshape", "GaussianNoise",
               "ActivityRegularization", "Masking"):
        # structural no-ops here: Flatten/Reshape via shape inference;
        # noise/regularization are identity at inference; Masking becomes an
        # explicit mask argument in this framework
        return None
    raise NotImplementedError(
        f"Keras layer {cls!r} not mapped; extend keras_import.py")


def _copy_weights(kl, layer, params: Dict[str, np.ndarray]) -> Dict:
    """Map Keras weight order to our param dict for one layer."""
    import jax.numpy as jnp
    w = kl.get_weights()
    cls = type(kl).__name__
    out = dict(params)
    if not w:
        return out
    if cls == "Dense":
        out["W"] = jnp.asarray(w[0])
        if len(w) > 1:
            out["b"] = jnp.asarray(w[1])
    elif cls in ("Conv2D", "Conv2DTranspose"):
        k = w[0]
        if cls == "Conv2DTranspose":
            # keras stores (kh, kw, out, in); ours is HWIO
            k = np.transpose(k, (0, 1, 3, 2))
        out["W"] = jnp.asarray(k)
        if len(w) > 1:
            out["b"] = jnp.asarray(w[1])
    elif cls == "SeparableConv2D":
        dw = w[0]  # (kh, kw, in, depth_mult) -> ours (kh, kw, 1, in*dm);
        # grouped-conv output channels are group-major (c*dm + d), which is
        # exactly the (in, dm) row-major flattening — no transpose
        kh, kw, cin, dm = dw.shape
        out["W_depth"] = jnp.asarray(dw.reshape(kh, kw, 1, cin * dm))
        out["W_point"] = jnp.asarray(w[1])
        if len(w) > 2:
            out["b"] = jnp.asarray(w[2])
    elif cls == "BatchNormalization":
        names = [v.name.split("/")[-1].split(":")[0] for v in kl.weights]
        for n, arr in zip(names, w):
            if "gamma" in n:
                out["gamma"] = jnp.asarray(arr)
            elif "beta" in n:
                out["beta"] = jnp.asarray(arr)
    elif cls == "Embedding":
        out["W"] = jnp.asarray(w[0])
    elif cls in ("LSTM", "GRU", "SimpleRNN"):
        # keras gate order LSTM [i,f,c,o] == ours [i,f,g,o]; GRU keras [z,r,h]
        _assign_rnn(out, w, gru=(cls == "GRU"))
    elif cls == "Bidirectional":
        half = len(w) // 2
        fwd = dict(out.get("fwd", {}))
        bwd = dict(out.get("bwd", {}))
        inner = getattr(kl, "layer", None) or kl.forward_layer
        gru = type(inner).__name__ == "GRU"
        _assign_rnn(fwd, w[:half], gru=gru)
        _assign_rnn(bwd, w[half:], gru=gru)
        out["fwd"], out["bwd"] = fwd, bwd
    elif cls == "Conv1D":
        out["W"] = jnp.asarray(w[0][:, None, :, :])  # (k, in, out) -> (k, 1, in, out)
        if len(w) > 1:
            out["b"] = jnp.asarray(w[1])
    elif cls == "Conv3D":
        out["W"] = jnp.asarray(w[0])  # keras DHWIO == ours
        if len(w) > 1:
            out["b"] = jnp.asarray(w[1])
    elif cls == "PReLU":
        out["alpha"] = jnp.asarray(w[0])
    elif cls == "TimeDistributed":
        out = _copy_weights(kl.layer, layer.underlying, out)
    elif cls == "SeparableConv1D":
        dw = w[0]  # (k, in, depth_mult) -> ours (k, 1, 1, in*dm), group-major
        k, cin, dm = dw.shape
        out["W_depth"] = jnp.asarray(dw.reshape(k, 1, 1, cin * dm))
        out["W_point"] = jnp.asarray(w[1][:, None, :, :])  # (1, in*dm, out)
        if len(w) > 2:
            out["b"] = jnp.asarray(w[2])
    elif cls == "LocallyConnected1D":
        # keras implementation=1 stores (out_t, k*in, filters)
        k0 = w[0]
        out["W"] = jnp.asarray(k0[:, None, :, :])
        if len(w) > 1:
            out["b"] = jnp.asarray(w[1].reshape(out["b"].shape)
                                   if "b" in out else w[1])
    elif cls == "LocallyConnected2D":
        k0 = w[0]  # (oh*ow, k*k*in, filters) or (oh, ow, ...)
        tgt = out["W"].shape
        out["W"] = jnp.asarray(np.asarray(k0).reshape(tgt))
        if len(w) > 1:
            out["b"] = jnp.asarray(np.asarray(w[1]).reshape(out["b"].shape))
    elif cls == "ConvLSTM2D":
        # keras gate order [i, f, c, o] == ours [i, f, g, o]
        out["W"] = jnp.asarray(w[0])
        out["W_rec"] = jnp.asarray(w[1])
        if len(w) > 2:
            out["b"] = jnp.asarray(w[2])
    return out


def _assign_rnn(d, w, gru: bool = False):
    import jax.numpy as jnp
    if gru:
        # keras packs [z(update), r(reset), h]; ours packs [r, u, n]
        def reorder(m):
            z, r, h = np.split(m, 3, axis=-1)
            return np.concatenate([r, z, h], axis=-1)
        d["W"] = jnp.asarray(reorder(w[0]))
        d["W_rec"] = jnp.asarray(reorder(w[1]))
        if len(w) > 2:
            b = w[2]
            if b.ndim == 2:
                # reset_after=True dual bias: input bias + RECURRENT bias —
                # the latter sits inside the reset product for the n gate
                # (CuDNN semantics), so it must stay separate
                d["b"] = jnp.asarray(reorder(b[0][None])[0])
                d["b_rec"] = jnp.asarray(reorder(b[1][None])[0])
            else:
                d["b"] = jnp.asarray(reorder(b[None])[0])
        return
    d["W"] = jnp.asarray(w[0])
    d["W_rec"] = jnp.asarray(w[1])
    if len(w) > 2:
        d["b"] = jnp.asarray(w[2])


def _input_type_of(km) -> InputType:
    shape = km.input_shape if not isinstance(km.input_shape, list) else km.input_shape[0]
    dims = [d for d in shape[1:]]
    if len(dims) == 4:
        return InputType.convolutional3d(dims[0], dims[1], dims[2], dims[3])
    if len(dims) == 3:
        return InputType.convolutional(dims[0], dims[1], dims[2])
    if len(dims) == 2:
        return InputType.recurrent(dims[1], dims[0])
    return InputType.feed_forward(dims[0])


def _import_sequential(km):
    from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork, _layer_key
    builder = NeuralNetConfiguration.builder().list()
    mapped: List = []
    keras_for_layer: List = []
    for kl in km.layers:
        layer = _map_layer(kl)
        if layer is None:
            continue
        builder.layer(layer)
        mapped.append(layer)
        keras_for_layer.append(kl)
    # last dense becomes OutputLayer for trainability (reference does the same
    # when loss is attached); keep as-is for inference-parity here.
    conf = builder.set_input_type(_input_type_of(km)).build()
    net = MultiLayerNetwork(conf).init()
    params = dict(net.train_state.params)
    state = dict(net.train_state.model_state)
    for i, (layer, kl) in enumerate(zip(mapped, keras_for_layer)):
        k = _layer_key(i, layer)
        if k in params or kl.get_weights():
            params[k] = _copy_weights(kl, layer, params.get(k, {}))
        if type(kl).__name__ == "BatchNormalization":
            w = kl.get_weights()
            names = [v.name.split("/")[-1].split(":")[0] for v in kl.weights]
            import jax.numpy as jnp
            st = dict(state.get(k, {}))
            for n, arr in zip(names, w):
                if "moving_mean" in n:
                    st["mean"] = jnp.asarray(arr)
                elif "moving_var" in n:
                    st["var"] = jnp.asarray(arr)
            state[k] = st
    import dataclasses
    net.train_state = dataclasses.replace(net.train_state, params=params,
                                          model_state=state)
    return net


def _import_functional(km):
    """Functional API -> ComputationGraph."""
    import tensorflow as tf
    from deeplearning4j_tpu.models.computation_graph import ComputationGraph
    from deeplearning4j_tpu.nn.graph_vertices import ElementWiseVertex, MergeVertex

    g = NeuralNetConfiguration.builder().graph_builder()
    input_names = [inp.name.split(":")[0] for inp in km.inputs]
    g.add_inputs(*input_names)
    types = []
    for inp in km.inputs:
        dims = [d for d in inp.shape[1:]]
        if len(dims) == 3:
            types.append(InputType.convolutional(dims[0], dims[1], dims[2]))
        elif len(dims) == 2:
            types.append(InputType.recurrent(dims[1], dims[0]))
        else:
            types.append(InputType.feed_forward(dims[0]))
    g.set_input_types(*types)

    name_map: Dict[str, str] = {}
    for inp, n in zip(km.inputs, input_names):
        name_map[_node_key(inp)] = n
    mapped_layers = {}
    for kl in km.layers:
        cls = type(kl).__name__
        if cls == "InputLayer":
            continue
        inbound = [_node_key(t) for t in _inbound_tensors(kl)]
        srcs = [name_map[k] for k in inbound]
        if cls == "Add":
            g.add_vertex(kl.name, ElementWiseVertex(op="add"), *srcs)
        elif cls == "Multiply":
            g.add_vertex(kl.name, ElementWiseVertex(op="mul"), *srcs)
        elif cls == "Average":
            g.add_vertex(kl.name, ElementWiseVertex(op="average"), *srcs)
        elif cls == "Subtract":
            g.add_vertex(kl.name, ElementWiseVertex(op="subtract"), *srcs)
        elif cls == "Maximum":
            g.add_vertex(kl.name, ElementWiseVertex(op="max"), *srcs)
        elif cls == "Minimum":
            g.add_vertex(kl.name, ElementWiseVertex(op="min"), *srcs)
        elif cls == "Dot":
            dcfg = kl.get_config()
            if dcfg.get("normalize"):
                raise NotImplementedError("Dot(normalize=True) not mapped")
            axes = dcfg.get("axes", -1)
            ax_set = {axes} if isinstance(axes, int) else set(axes)
            # the vertex contracts the LAST axis; anything else (batch_dot
            # over middle axes) is a different computation — fail loudly
            if not ax_set <= {-1, 1}:
                raise NotImplementedError(
                    f"Dot(axes={axes}) not mapped (last-axis only)")
            g.add_vertex(kl.name, ElementWiseVertex(op="dot"), *srcs)
        elif cls == "Concatenate":
            g.add_vertex(kl.name, MergeVertex(), *srcs)
        elif cls == "Flatten":
            from deeplearning4j_tpu.nn.graph_vertices import PreprocessorVertex
            g.add_vertex(kl.name, PreprocessorVertex(CnnToFeedForwardPreProcessor()), *srcs)
        else:
            layer = _map_layer(kl)
            if layer is None:
                name_map[_node_key(kl.output)] = srcs[0]
                continue
            g.add_layer(kl.name, layer, *srcs)
            mapped_layers[kl.name] = (kl, layer)
        name_map[_node_key(kl.output)] = kl.name
    outputs = [name_map[_node_key(t)] for t in km.outputs]
    g.set_outputs(*outputs)
    net = ComputationGraph(g.build()).init()
    params = dict(net.train_state.params)
    state = dict(net.train_state.model_state)
    import dataclasses
    import jax.numpy as jnp
    for name, (kl, layer) in mapped_layers.items():
        if name in params or kl.get_weights():
            params[name] = _copy_weights(kl, layer, params.get(name, {}))
        if type(kl).__name__ == "BatchNormalization":
            names = [v.name.split("/")[-1].split(":")[0] for v in kl.weights]
            st = dict(state.get(name, {}))
            for n, arr in zip(names, kl.get_weights()):
                if "moving_mean" in n:
                    st["mean"] = jnp.asarray(arr)
                elif "moving_var" in n:
                    st["var"] = jnp.asarray(arr)
            state[name] = st
    net.train_state = dataclasses.replace(net.train_state, params=params,
                                          model_state=state)
    return net


def _node_key(tensor) -> str:
    return tensor.name if hasattr(tensor, "name") else str(id(tensor))


def _inbound_tensors(kl):
    inp = kl.input
    return inp if isinstance(inp, list) else [inp]


# ------------------------------------------------------------- Keras 1.x
# (reference: the keras-import module handles both 1.x and 2.x dialects —
# `org.deeplearning4j.nn.modelimport.keras` KerasLayerConfiguration has
# per-version field tables. Modern tf.keras refuses 1.x archives entirely,
# so this path parses the H5 directly.)


def _is_keras1_h5(path: str) -> bool:
    import zipfile
    if zipfile.is_zipfile(path):
        return False  # .keras archives are v3
    try:
        import h5py
        with h5py.File(path, "r") as f:
            ver = f.attrs.get("keras_version", b"")
            if isinstance(ver, bytes):
                ver = ver.decode()
            return str(ver).startswith("1.")
    except Exception:
        return False


def _k1_act(name):
    return {"linear": "identity"}.get(name or "linear", name or "identity")


def _map_keras1_layer(cls: str, cfg: Dict):
    """Keras 1.x dialect -> our layer configs (nb_filter/border_mode/
    subsample/output_dim era field names)."""
    if cls == "Dense":
        return DenseLayer(n_out=cfg["output_dim"],
                          activation=_k1_act(cfg.get("activation")),
                          has_bias=cfg.get("bias", True))
    if cls == "Convolution2D":
        if cfg.get("dim_ordering", "tf") == "th":
            raise NotImplementedError(
                "Keras 1 dim_ordering='th' (channels-first) not supported")
        return ConvolutionLayer(
            n_out=cfg["nb_filter"],
            kernel_size=(cfg["nb_row"], cfg["nb_col"]),
            stride=tuple(cfg.get("subsample", (1, 1))),
            convolution_mode="same" if cfg.get("border_mode") == "same"
            else "truncate",
            activation=_k1_act(cfg.get("activation")),
            has_bias=cfg.get("bias", True))
    if cls == "MaxPooling2D" or cls == "AveragePooling2D":
        return SubsamplingLayer(
            pooling_type=PoolingType.MAX if cls.startswith("Max")
            else PoolingType.AVG,
            kernel_size=tuple(cfg.get("pool_size", (2, 2))),
            stride=tuple(cfg.get("strides") or cfg.get("pool_size", (2, 2))),
            convolution_mode="same" if cfg.get("border_mode") == "same"
            else "truncate")
    if cls == "Activation":
        return ActivationLayer(activation=_k1_act(cfg.get("activation")))
    if cls == "Dropout":
        return DropoutLayer(dropout=1.0 - cfg.get("p", 0.5))
    if cls == "Flatten":
        from deeplearning4j_tpu.nn import FlattenLayer
        return FlattenLayer()
    if cls == "Embedding":
        return EmbeddingSequenceLayer(n_in=cfg["input_dim"],
                                      n_out=cfg["output_dim"])
    if cls == "LSTM":
        if cfg.get("inner_activation", "hard_sigmoid") not in ("hard_sigmoid",
                                                               "sigmoid"):
            raise NotImplementedError(
                f"Keras 1 LSTM inner_activation {cfg['inner_activation']!r}")
        return LSTM(n_out=cfg["output_dim"],
                    activation=_k1_act(cfg.get("activation", "tanh")),
                    gate_activation=cfg.get("inner_activation", "hard_sigmoid"))
    if cls == "GRU":
        # Keras 1 GRU is the reset-BEFORE variant (tanh(x_h + (r*h) @ U_h))
        # with hard_sigmoid gates — GRU(reset_after=False) implements
        # exactly that cell (round 3; formerly refused)
        return GRU(n_out=cfg["output_dim"],
                   activation=_k1_act(cfg.get("activation", "tanh")),
                   gate_activation=cfg.get("inner_activation", "hard_sigmoid"),
                   reset_after=False)
    raise NotImplementedError(
        f"Keras 1 layer {cls!r} not mapped; extend keras_import.py")


def _keras1_input_type(first_cfg: Dict, first_cls: str):
    shape = first_cfg.get("batch_input_shape")
    if shape is None:
        raise ValueError("Keras 1 model lacks batch_input_shape on layer 0")
    dims = [d for d in shape[1:]]
    if len(dims) == 3:
        return InputType.convolutional(dims[0], dims[1], dims[2])
    if len(dims) == 2:
        return InputType.recurrent(dims[1], dims[0])
    return InputType.feed_forward(dims[0])


def _import_keras1_h5(path: str):
    import dataclasses as _dc
    import json

    import h5py
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork

    with h5py.File(path, "r") as f:
        raw = f.attrs["model_config"]
        if isinstance(raw, bytes):
            raw = raw.decode()
        mc = json.loads(raw)
        if isinstance(mc, dict) and mc.get("class_name") not in (None, "Sequential"):
            raise NotImplementedError(
                "Keras 1 import supports Sequential models")
        layer_cfgs = mc["config"] if isinstance(mc, dict) else mc

        mapped = [(lc["class_name"], lc["config"],
                   _map_keras1_layer(lc["class_name"], lc["config"]))
                  for lc in layer_cfgs]

        it0 = _keras1_input_type(layer_cfgs[0]["config"],
                                 layer_cfgs[0]["class_name"])
        b = NeuralNetConfiguration.builder().list()
        for _, _, layer in mapped:
            b = b.layer(layer)
        conf = b.set_input_type(it0).build()
        net = MultiLayerNetwork(conf).init()

        # weights: keras 1 stores one group per layer with a weight_names attr
        wroot = f["model_weights"] if "model_weights" in f else f
        params = dict(net.train_state.params)
        for li, (cls, cfg, _) in enumerate(mapped):
            name = cfg.get("name")
            key = f"layer_{li}"
            if name not in wroot:
                continue
            g = wroot[name]
            wnames = [n.decode() if isinstance(n, bytes) else n
                      for n in g.attrs.get("weight_names", [])]
            arrs = [np.asarray(g[n]) for n in wnames] if wnames else \
                [np.asarray(g[n]) for n in sorted(g.keys())]
            if not arrs:
                continue
            p = dict(params.get(key, {}))
            if cls in ("Dense", "Convolution2D"):
                # keras 1 tf-ordering conv kernels are (rows, cols, in, out)
                # == our HWIO; Dense is (in, out) == ours
                p["W"] = jnp.asarray(arrs[0])
                if len(arrs) > 1:
                    p["b"] = jnp.asarray(arrs[1])
            elif cls == "Embedding":
                p["W"] = jnp.asarray(arrs[0])
            elif cls == "LSTM" and len(arrs) == 12:
                # keras 1 stores PER-GATE matrices [W_i,U_i,b_i, W_c,U_c,b_c,
                # W_f,U_f,b_f, W_o,U_o,b_o]; ours packs [i, f, g(c), o]
                Wi, Ui, bi, Wc, Uc, bc, Wf, Uf, bf, Wo, Uo, bo = arrs
                p["W"] = jnp.asarray(np.concatenate([Wi, Wf, Wc, Wo], 1))
                p["W_rec"] = jnp.asarray(np.concatenate([Ui, Uf, Uc, Uo], 1))
                p["b"] = jnp.asarray(np.concatenate([bi, bf, bc, bo]))
            elif cls == "GRU" and len(arrs) == 9:
                # keras 1 GRU per-gate matrices [W_z,U_z,b_z, W_r,U_r,b_r,
                # W_h,U_h,b_h]; ours packs [r, u(z), n(h)] (reset-before
                # cell via GRU(reset_after=False))
                Wz, Uz, bz, Wr, Ur, br, Wh, Uh, bh = arrs
                p["W"] = jnp.asarray(np.concatenate([Wr, Wz, Wh], 1))
                p["W_rec"] = jnp.asarray(np.concatenate([Ur, Uz, Uh], 1))
                p["b"] = jnp.asarray(np.concatenate([br, bz, bh]))
            elif cls in ("LSTM", "GRU"):
                # silent fall-through would keep RANDOM init — refuse loudly
                raise NotImplementedError(
                    f"Keras 1 {cls} stored {len(arrs)} weight arrays; only "
                    f"the per-gate layout ({12 if cls == 'LSTM' else 9} "
                    "arrays, consume_less='cpu'/'mem') is supported — "
                    "re-save the model with consume_less='cpu'")
            params[key] = p
        net.train_state = _dc.replace(net.train_state, params=params)
    return net
