"""TF GraphDef import.

Rebuild of upstream ``org.nd4j.imports.graphmapper.tf.TFGraphMapper``
(SURVEY.md §3.3): parse a frozen GraphDef, constant-fold ``Const`` nodes,
map each node to a registry op on a SameDiff-equivalent graph. The op set
covers the BERT-base inference/fine-tune graph (matmul/batched-matmul,
gather, strided-slice, layernorm building blocks, softmax, gelu-via-erf,
reshape/transpose family) plus the common CNN ops.

Static-attr folding: TF passes shapes/axes as Const *tensor inputs*; the
importer resolves those at import time into op attrs (the reference does the
same in each op's ``initFromTensorFlow``), so the resulting graph is
shape-static and jit-compiles cleanly.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.autodiff.samediff import SameDiff, VariableType


def _tf():
    import tensorflow as tf
    return tf


class TFGraphMapper:
    @staticmethod
    def import_graph(path_or_graphdef, input_shapes: Optional[Dict[str, tuple]] = None,
                     optimize: bool = True,
                     while_max_iterations: Optional[int] = None,
                     lazy_conditionals: bool = True) -> SameDiff:
        """Import a frozen .pb file (or a GraphDef proto) into a SameDiff.
        ``optimize`` runs the graph-optimizer fusion passes (layernorm/gelu
        patterns -> fused ops; reference: libnd4j's pre-execution graph
        optimization). ``while_max_iterations``: when set, every imported
        While loop (functional or TF1 frames) lowers to a fixed-length
        masked ``lax.scan`` of that length instead of ``lax.while_loop`` —
        the scan form is reverse-differentiable, so graphs containing loops
        can be fine-tuned with ``sd.fit`` (the while form is forward-only,
        as in JAX). ``lazy_conditionals``: TF1 Switch/Merge conditionals
        lower onto ``sd.cond`` (only the taken branch executes); pass
        False for the execute-both + where form, which costs up to 2x the
        taken branch's work but keeps the graph free of python callables —
        required if the imported graph must round-trip ``sd.save()``."""
        tf = _tf()
        if isinstance(path_or_graphdef, (str, bytes)):
            gd = tf.compat.v1.GraphDef()
            with open(path_or_graphdef, "rb") as f:
                gd.ParseFromString(f.read())
        else:
            gd = path_or_graphdef
        imp = _GraphImporter(gd, input_shapes or {})
        imp.while_max_iterations = while_max_iterations
        imp.lazy_conditionals = lazy_conditionals
        sd = imp.run()
        if optimize:
            from deeplearning4j_tpu.autodiff.graph_optimizer import (
                optimize as _opt)
            _opt(sd)
        return sd

    @staticmethod
    def import_saved_model(path: str, signature: str = "serving_default",
                           input_shapes: Optional[Dict[str, tuple]] = None,
                           optimize: bool = True):
        """Load a TF2 SavedModel, freeze the named signature, import it
        (same pipeline as :meth:`import_graph`, optimizer passes included).
        Returns ``(sd, input_names, output_names)`` (the reference's
        SavedModel entry point on TFGraphMapper)."""
        tf = _tf()
        from tensorflow.python.framework.convert_to_constants import (
            convert_variables_to_constants_v2)
        sm = tf.saved_model.load(path)
        fn = sm.signatures[signature]
        frozen = convert_variables_to_constants_v2(fn)
        gd = frozen.graph.as_graph_def()
        sd = TFGraphMapper.import_graph(gd, input_shapes, optimize=optimize)
        inputs = [t.name.split(":")[0] for t in frozen.inputs
                  if t.dtype != tf.resource]
        outputs = [t.name.split(":")[0] for t in frozen.outputs]
        return sd, inputs, outputs


def _flatten_ref(ref: str) -> str:
    """FunctionDef input ref ('node:tag:idx', 'node:idx' or 'arg') to
    top-level GraphDef form ('node' / 'node:idx')."""
    ctrl = ref.startswith("^")
    if ctrl:
        ref = ref[1:]
    parts = ref.split(":")
    if len(parts) == 1:
        out = parts[0]
    else:
        idx = parts[-1]
        out = parts[0] if idx == "0" or not idx.isdigit() else f"{parts[0]}:{idx}"
    return ("^" + out) if ctrl else out


class _GraphImporter:
    def __init__(self, graph_def, input_shapes: Dict[str, tuple]):
        self.gd = graph_def
        self.input_shapes = input_shapes
        self.sd = SameDiff.create()
        self.const_values: Dict[str, np.ndarray] = {}
        self.node_by_name = {n.name: n for n in self.gd.node}
        # TF2 function library (While/If bodies, PartitionedCall targets)
        self.functions = {f.signature.name: f
                          for f in graph_def.library.function}
        self._switch_pred: Dict[str, str] = {}   # Switch node -> pred ref
        self._switch_memo: Dict[str, Optional[tuple]] = {}
        self._consumers: Optional[Dict[str, list]] = None  # lazy fwd edges
        # TF1 while frames: nodes consumed by a lowered frame are skipped
        # by the per-node loop (the frame's cond/body are re-imported as
        # standalone subgraphs feeding sd.while_loop)
        self._frame_consumed: set = set()
        # opt-in: lower While loops to fixed-length differentiable scans
        self.while_max_iterations: Optional[int] = None
        # TF1 Switch/Merge conds -> sd.cond (lazy); False = where-select
        # (keeps the graph serializable via sd.save)
        self.lazy_conditionals: bool = True

    # --- helpers ---
    @staticmethod
    def _clean(name: str) -> str:
        name = name.split(":")[0]
        return name[1:] if name.startswith("^") else name

    def _const(self, name: str) -> np.ndarray:
        """Resolve a (possibly Identity-wrapped) constant input's value."""
        name = self._clean(name)
        if name in self.const_values:
            return self.const_values[name]
        node = self.node_by_name.get(name)
        if node is not None and node.op in ("Identity", "Cast", "StopGradient"):
            return self._const(node.input[0])
        raise ValueError(f"Input {name!r} is not a constant (op="
                         f"{node.op if node else '?'}) — cannot fold statically")

    def _attr(self, node, key, default=None):
        if key not in node.attr:
            return default
        a = node.attr[key]
        kind = a.WhichOneof("value")
        if kind == "i":
            return int(a.i)
        if kind == "f":
            return float(a.f)
        if kind == "b":
            return bool(a.b)
        if kind == "s":
            return a.s.decode()
        if kind == "type":
            return _tf().dtypes.as_dtype(a.type).name
        if kind == "shape":
            return tuple(d.size for d in a.shape.dim)
        if kind == "list":
            return list(a.list.i) or list(a.list.f) or [s.decode() for s in a.list.s]
        return default

    def _tf_seed(self, node) -> int:
        """Stream seed for a TF random node. Seeded ops keep their (seed,
        seed2) pair; unseeded ops (seed=seed2=0, TF draws nondeterministic)
        get a stable per-node stream from the node name, so two dropout
        sites never share draws while the import stays reproducible. For
        stateless ops the seed/key operand joins the hash when constant."""
        import zlib
        s1 = int(self._attr(node, "seed", 0) or 0)
        s2 = int(self._attr(node, "seed2", 0) or 0)
        if s1 or s2:
            return (s1 * 2654435761 + s2) & 0x7FFFFFFF
        h = zlib.crc32(node.name.encode())
        if node.op.startswith("Stateless") and len(node.input) > 1:
            try:
                h ^= zlib.crc32(np.ascontiguousarray(
                    self._const(node.input[1])).tobytes())
            except ValueError:
                pass
        return h & 0x7FFFFFFF

    def _ensure_var(self, name: str) -> str:
        """Map a TF input ref to an sd variable name (materialising consts)."""
        raw = name[1:] if name.startswith("^") else name
        if raw in self.sd.vars:
            return raw  # exact match, incl. multi-output refs like "while:1"
        name = self._clean(name)
        if name in self.sd.vars:
            return name
        if name in self.const_values:
            arr = self.const_values[name]
            v = self.sd.constant(name, arr)
            # constant() may uniquify; force exact name mapping
            if v.name != name:
                v.rename(name)
            return name
        raise ValueError(f"Unresolved input {name!r}")

    def _emit(self, node, op: str, inputs: List[str], **attrs):
        vars_ = [self.sd.vars[self._ensure_var(i)] for i in inputs]
        out = self.sd._apply(op, vars_, attrs=attrs or None, name=node.name)
        if out.name != node.name:
            out.rename(node.name)
        return out

    # --- main loop ---
    def run(self) -> SameDiff:
        tf = _tf()
        from tensorflow.python.framework import tensor_util

        for node in self.gd.node:
            if node.op == "Const":
                self.const_values[node.name] = tensor_util.MakeNdarray(
                    node.attr["value"].tensor)
        for node in self.gd.node:
            self._map_node(node)
        return self.sd

    def _inputs(self, node) -> List[str]:
        return [i for i in node.input if not i.startswith("^")]

    def _controlling_switch(self, ref: str) -> Optional[tuple]:
        """Walk ancestors of ``ref`` to the nearest Switch; returns
        (switch_name, taken_output_index) or None."""
        if ref in self._switch_memo:
            return self._switch_memo[ref]
        self._switch_memo[ref] = None  # cycle guard
        ref2 = ref[1:] if ref.startswith("^") else ref
        name, _, idx = ref2.partition(":")
        node = self.node_by_name.get(name)
        res = None
        if node is not None:
            if node.op == "Switch":
                res = (name, int(idx) if idx else 0)
            else:
                for i in self._inputs(node):
                    res = self._controlling_switch(i)
                    if res:
                        break
        self._switch_memo[ref] = res
        return res

    def _name_outputs(self, node, outs) -> None:
        """Rename emitted vars to TF's multi-output convention
        (``name``, ``name:1``, ...)."""
        for i, o in enumerate(outs):
            want = node.name if i == 0 else f"{node.name}:{i}"
            if o.name != want:
                o.rename(want)

    # ---- TF2 function library support ----
    def _inline_call(self, node, fname: str, ins: List[str]) -> None:
        """Inline a (Stateful)PartitionedCall: splice the FunctionDef body
        into this graph under the call node's name prefix (the reference
        inlines function graphs the same way before mapping)."""
        from tensorflow.python.framework import tensor_util
        fdef = self.functions.get(fname)
        if fdef is None:
            raise NotImplementedError(f"Call to unknown function {fname!r}")
        prefix = node.name
        arg_map = {arg.name: caller_in
                   for arg, caller_in in zip(fdef.signature.input_arg, ins)}

        def rewrite(ref: str) -> str:
            ctrl = ref.startswith("^")
            flat = _flatten_ref(ref)
            if ctrl:
                flat = flat[1:]
            base, _, idx = flat.partition(":")
            mapped = arg_map.get(base, f"{prefix}/{base}")
            out = mapped if not idx else f"{mapped}:{idx}"
            return ("^" + out) if ctrl else out

        new_nodes = []
        for nd in fdef.node_def:
            cp = type(nd)()
            cp.CopyFrom(nd)
            cp.name = f"{prefix}/{nd.name}"
            del cp.input[:]
            cp.input.extend(rewrite(r) for r in nd.input)
            new_nodes.append(cp)
        for nd in new_nodes:
            self.node_by_name[nd.name] = nd
            if nd.op == "Const":
                self.const_values[nd.name] = tensor_util.MakeNdarray(
                    nd.attr["value"].tensor)
        for nd in new_nodes:
            self._map_node(nd)
        # alias the call's outputs to the body's return values
        for j, out_arg in enumerate(fdef.signature.output_arg):
            src = rewrite(fdef.ret[out_arg.name])
            want = node.name if j == 0 else f"{node.name}:{j}"
            self._alias(want, src)

    def _alias(self, want: str, src: str) -> None:
        src = self._clean(src) if ":" not in src or src.split(":")[-1] == "0" \
            else src
        if src in self.const_values and src not in self.sd.vars:
            self.const_values[want] = self.const_values[src]
            return
        v = self.sd._apply("identity", [self.sd.vars[self._ensure_var(src)]],
                           name=want)
        if v.name != want:
            v.rename(want)

    def _function_subgraph(self, fname: str):
        """Materialise a FunctionDef as a standalone GraphDef + import it;
        returns (sub_sd, input_names, output_names)."""
        tf = _tf()
        fdef = self.functions.get(fname)
        if fdef is None:
            raise NotImplementedError(f"Unknown function {fname!r}")
        gd2 = tf.compat.v1.GraphDef()
        gd2.library.CopyFrom(self.gd.library)  # nested calls resolve too
        input_names = []
        for arg in fdef.signature.input_arg:
            nd = gd2.node.add()
            nd.name = arg.name
            nd.op = "Placeholder"
            nd.attr["dtype"].type = arg.type
            input_names.append(arg.name)
        for body_node in fdef.node_def:
            cp = gd2.node.add()
            cp.CopyFrom(body_node)
            del cp.input[:]
            cp.input.extend(_flatten_ref(r) for r in body_node.input)
        output_names = [_flatten_ref(fdef.ret[o.name])
                        for o in fdef.signature.output_arg]
        sub_sd = _GraphImporter(gd2, {}).run()
        return sub_sd, input_names, output_names

    def _function_callable(self, fname: str):
        """FunctionDef -> python callable on jax arrays (feeds sd.while_loop
        / sd.cond, which lower to lax.while_loop / lax.cond). Accepts an
        optional per-step ``key`` so stochastic ops INSIDE control-flow
        bodies (dropout in a While body, training=True) stay live during
        sd.fit — the sub-executor re-injects per-node subkeys from it."""
        sub_sd, in_names, out_names = self._function_subgraph(fname)

        def fn(*arrays, key=None):
            env = dict(sub_sd.arrays)
            env.update(zip(in_names, arrays))
            if key is not None:
                env["__rng__"] = key
            return sub_sd._exec_graph(env, out_names)

        fn._accepts_rng = True
        return fn

    # ---- TF1 lowered tf.cond (Switch/Merge dataflow) → lazy sd.cond ----
    def _forward_reachable(self, roots) -> set:
        """Node names forward-reachable from ``roots`` along data/control
        edges — the region a Switch can gate."""
        if self._consumers is None:
            cons: Dict[str, list] = {}
            for n in self.gd.node:
                for i in n.input:
                    cons.setdefault(self._clean(i), []).append(n.name)
            self._consumers = cons
        seen: set = set()
        stack = list(roots)
        while stack:
            nm = stack.pop()
            if nm in seen:
                continue
            seen.add(nm)
            stack.extend(self._consumers.get(nm, ()))
        return seen

    def _cond_branch_callable(self, root_ref: str, switches: set, reach: set):
        """Backward-slice ONE tf.cond branch from a Merge input and build a
        jax callable for it (reference ``TFGraphMapper`` keeps Switch/Merge
        as SameDiff frame ops with lazy branch execution; here the branch
        subgraph is re-imported standalone and lowered onto ``sd.cond`` →
        ``lax.cond``). Boundaries become Placeholders: a branch Switch is
        fed by its data input (computed unconditionally — exactly
        lax.cond's operand semantics, and TF's: Switch inputs run before
        the branch), and any value produced outside the Switch-gated
        region is fed as-is. Returns ``(fn, feed_refs)`` where
        ``feed_refs[i]`` is the outer-graph ref supplying operand i."""
        tf = _tf()
        stops: Dict[str, str] = {}   # canonical boundary ref -> placeholder
        feeds: list = []             # outer feed ref per placeholder
        interior: Dict[str, Any] = {}
        inline_consts: Dict[str, np.ndarray] = {}

        def canon(ref: str):
            """(key, feed) for a boundary ref, or None if interior."""
            base = self._clean(ref)
            if base in switches:
                # both Switch outputs carry the same data value
                return base, self.node_by_name[base].input[0]
            if base not in reach and base not in inline_consts:
                flat = _flatten_ref(ref[1:] if ref.startswith("^") else ref)
                return flat, flat
            return None

        stack = [root_ref]
        while stack:
            ref = stack.pop()
            if ref.startswith("^"):
                continue  # ordering-only edges; graphs here are pure
            base = self._clean(ref)
            if base in switches:
                if base not in stops:
                    stops[base] = f"__cb_{len(stops)}"
                    feeds.append(self.node_by_name[base].input[0])
                continue
            if base not in reach:
                if base in inline_consts:
                    continue
                # Outside-region constants are INLINED into the branch
                # subgraph (not fed as operands): branch ops that
                # static-fold an operand — Mean/Reshape axes, shapes —
                # must still see a Const, not a Placeholder.
                try:
                    inline_consts[base] = self._const(ref)
                    continue
                except ValueError:
                    pass
                flat = _flatten_ref(ref)
                if flat not in stops:
                    stops[flat] = f"__cb_{len(stops)}"
                    feeds.append(flat)
                continue
            if base in interior:
                continue
            node = self.node_by_name.get(base)
            if node is None:
                raise NotImplementedError(
                    f"cond branch references unknown node {base!r}")
            interior[base] = node
            stack.extend(node.input)

        # topo-sort the slice (sub-importer maps in list order); a nested
        # while frame's Merge <- NextIteration back-edge is dropped, as in
        # the frame machinery — the sub-importer re-discovers the loop
        def _deps(n):
            out = []
            for d in (self._clean(i) for i in n.input):
                if d not in interior:
                    continue
                if n.op == "Merge" and \
                        self.node_by_name[d].op == "NextIteration":
                    continue
                out.append(d)
            return out

        deps = {nm: _deps(n) for nm, n in interior.items()}
        done: set = set()
        order: list = []

        def visit(nm, chain=()):
            if nm in done:
                return
            if nm in chain:
                raise NotImplementedError(
                    f"cycle through {nm!r} in cond branch slice")
            for d in deps[nm]:
                visit(d, chain + (nm,))
            done.add(nm)
            order.append(interior[nm])

        for nm in interior:
            visit(nm)

        gd2 = tf.compat.v1.GraphDef()
        gd2.library.CopyFrom(self.gd.library)
        for key, ph in stops.items():
            nd = gd2.node.add()
            nd.name = ph
            nd.op = "Placeholder"
        for cname, cval in inline_consts.items():
            nd = gd2.node.add()
            nd.name = cname
            nd.op = "Const"
            nd.attr["value"].tensor.CopyFrom(tf.make_tensor_proto(cval))
            nd.attr["dtype"].type = nd.attr["value"].tensor.dtype
        for node in order:
            cp = gd2.node.add()
            cp.CopyFrom(node)
            del cp.input[:]
            for ref in node.input:
                if ref.startswith("^"):
                    if self._clean(ref) in interior:
                        cp.input.append(ref)
                    continue
                cb = canon(ref)
                cp.input.append(stops[cb[0]] if cb is not None else ref)
        cb = canon(root_ref)
        out_ref = stops[cb[0]] if cb is not None else _flatten_ref(root_ref)
        sub_sd = _GraphImporter(gd2, {}).run()
        ph_names = list(stops.values())

        def fn(*arrays, key=None):
            env = dict(sub_sd.arrays)
            env.update(zip(ph_names, arrays))
            if key is not None:
                env["__rng__"] = key
            return sub_sd._exec_graph(env, [out_ref])[0]

        fn._accepts_rng = True
        return fn, feeds

    def _lower_cond_merge(self, node, true_ref: str, false_ref: str,
                          pred_ref: str) -> None:
        """Lower one matched Switch/Merge conditional onto ``sd.cond``:
        only the taken branch executes (lax.cond), unlike the
        execute-both + ``where`` fallback. The branch nodes eagerly mapped
        before this Merge was reached become dead code — ``_exec_graph``
        is demand-driven and never computes them."""
        sd = self.sd
        pflat = _flatten_ref(pred_ref)
        switches = {s for s, p in self._switch_pred.items()
                    if _flatten_ref(p) == pflat}
        reach = self._forward_reachable(switches)
        tfn, tfeeds = self._cond_branch_callable(true_ref, switches, reach)
        ffn, ffeeds = self._cond_branch_callable(false_ref, switches, reach)
        feeds = list(dict.fromkeys(tfeeds + ffeeds))
        t_idx = [feeds.index(r) for r in tfeeds]
        f_idx = [feeds.index(r) for r in ffeeds]

        def true_fn(*a, key=None):
            return tfn(*[a[i] for i in t_idx], key=key)

        def false_fn(*a, key=None):
            return ffn(*[a[i] for i in f_idx], key=key)

        true_fn._accepts_rng = True
        false_fn._accepts_rng = True
        out = sd.cond(sd.vars[self._ensure_var(pred_ref)], true_fn, false_fn,
                      *[sd.vars[self._ensure_var(r)] for r in feeds],
                      name=node.name)
        if out.name != node.name:
            out.rename(node.name)

    # ---- TF1 while-loop frames (Enter/Merge/Switch/NextIteration/Exit) ----
    def _extract_frame_subgraph(self, roots: List[str], stops: Dict[str, str],
                                frame_nodes: set):
        """Backward-slice the main graph from ``roots``, stopping at names
        in ``stops`` (ref base name -> placeholder name). Returns
        (interior node list in graph order, used stop names)."""
        interior, used, seen = [], set(), set()
        stack = [self._clean(r) for r in roots]
        while stack:
            name = stack.pop()
            if name in seen:
                continue
            seen.add(name)
            if name in stops:
                used.add(name)
                continue
            node = self.node_by_name.get(name)
            if node is None:
                continue
            # Frame ops reached here belong to a NESTED while (the current
            # frame's own Merge/Switch/Enter are stop points): include the
            # whole inner frame in the slice — the sub-importer lowers it
            # recursively when it meets the inner Enter. Same-frame ops
            # reached outside the carry chain are malformed and will hit
            # the sub-importer's orphan-frame-op check.
            interior.append(node)
            frame_nodes.add(name)
            stack.extend(self._clean(i) for i in node.input)
        # TOPO-sort the slice: graphs lowered from functional control flow
        # (convert_variables_to_constants_v2 lowers While to v1 frames) are
        # NOT topologically ordered, and the sub-importer maps nodes in
        # list order
        names = {n.name for n in interior}

        def _deps(n):
            out = []
            for d in (self._clean(i) for i in n.input):
                if d not in names:
                    continue
                # a nested frame's Merge <- NextIteration edge is the
                # loop's back-edge; dropping it makes the slice acyclic
                # (the sub-importer re-discovers the loop structure)
                if n.op == "Merge" and \
                        self.node_by_name[d].op == "NextIteration":
                    continue
                out.append(d)
            return out

        deps = {n.name: _deps(n) for n in interior}
        done, out_order, nodes_by = set(), [], {n.name: n for n in interior}
        def visit(nm, chain=()):
            if nm in done:
                return
            if nm in chain:
                raise NotImplementedError(
                    f"cycle through {nm!r} in frame slice")
            for d in deps[nm]:
                visit(d, chain + (nm,))
            done.add(nm)
            out_order.append(nodes_by[nm])
        for n in interior:
            visit(n.name)
        return out_order, used

    def _frame_subgraph_callable(self, roots: List[str],
                                 stops: Dict[str, str], frame_nodes: set):
        """Build a jax callable for a frame's cond or body slice: stop
        points become Placeholders fed by the loop carries, interior nodes
        are re-imported as a standalone graph."""
        tf = _tf()
        interior, _ = self._extract_frame_subgraph(roots, stops, frame_nodes)
        gd2 = tf.compat.v1.GraphDef()
        gd2.library.CopyFrom(self.gd.library)
        for base, ph in stops.items():
            nd = gd2.node.add()
            nd.name = ph
            nd.op = "Placeholder"
        for node in interior:
            cp = gd2.node.add()
            cp.CopyFrom(node)
            del cp.input[:]
            for ref in node.input:
                if ref.startswith("^"):
                    base = self._clean(ref)
                    if base in stops or base not in {n.name for n in interior}:
                        continue  # control dep to outside the slice
                    cp.input.append(ref)
                    continue
                base, _, idx = ref.partition(":")
                if base in stops:
                    cp.input.append(stops[base])
                else:
                    cp.input.append(ref)
        out_refs = []
        for r in roots:
            base, _, idx = r.partition(":")
            out_refs.append(stops.get(base, r) if base in stops else r)
        sub_sd = _GraphImporter(gd2, {}).run()
        ph_names = [stops[b] for b in stops]

        def fn(*arrays, key=None):
            env = dict(sub_sd.arrays)
            env.update(zip(ph_names, arrays))
            if key is not None:
                env["__rng__"] = key
            return sub_sd._exec_graph(env, out_refs)

        fn._accepts_rng = True
        return fn, list(stops)

    def _lower_tf1_frame(self, frame: str) -> None:
        """Reconstruct one TF1 while frame and lower it onto
        ``sd.while_loop`` (upstream ``TFGraphMapper`` + SameDiff frame ops;
        SURVEY.md §3.3). Carries = Merge chains; loop-invariant Enters ride
        along as carries the body returns unchanged. Default lowering is
        ``lax.while_loop`` (forward-only, like the functional While path);
        pass ``while_max_iterations`` to ``import_graph`` for the
        differentiable fixed-length scan form."""
        enters = [n for n in self.gd.node
                  if n.op == "Enter" and self._attr(n, "frame_name") == frame]
        enter_names = {n.name for n in enters}
        merges = {}
        for n in self.gd.node:
            if n.op == "Merge":
                ins = self._inputs(n)
                if ins and any(self._clean(i) in enter_names for i in ins):
                    merges[n.name] = n
        if not merges:
            raise NotImplementedError(
                f"TF1 frame {frame!r}: Enter nodes without Merge carries")
        switches = {}
        loopcond_name = None
        for n in self.gd.node:
            if n.op == "Switch":
                ins = self._inputs(n)
                if len(ins) == 2 and self._clean(ins[0]) in merges:
                    switches[self._clean(ins[0])] = n
                    loopcond_name = self._clean(ins[1])
        if loopcond_name is None:
            raise NotImplementedError(
                f"TF1 frame {frame!r}: no Switch keyed on a LoopCond")
        loopcond = self.node_by_name[loopcond_name]
        frame_nodes = set(enter_names) | set(merges) | {loopcond_name}
        frame_nodes.update(s.name for s in switches.values())

        # per-carry bookkeeping, deterministic order
        carry_names = sorted(merges)
        next_refs, exit_nodes, enter_of = [], [], []
        for mname in carry_names:
            ins = self._inputs(merges[mname])
            e = next(self._clean(i) for i in ins
                     if self._clean(i) in enter_names)
            ni = next(self._clean(i) for i in ins
                      if self._clean(i) not in enter_names)
            ni_node = self.node_by_name.get(ni)
            if ni_node is None or ni_node.op != "NextIteration":
                raise NotImplementedError(
                    f"TF1 frame {frame!r}: Merge {mname!r} second input is "
                    f"{ni!r}, not a NextIteration")
            enter_of.append(e)
            next_refs.append(ni_node.input[0])
            frame_nodes.add(ni)
            sw = switches.get(mname)
            ex = None
            if sw is not None:
                for n in self.gd.node:
                    if n.op == "Exit" and \
                            self._clean(self._inputs(n)[0]) == sw.name:
                        ex = n
                        frame_nodes.add(n.name)
                        break
            exit_nodes.append(ex)
        invariants = sorted(enter_names - set(enter_of))

        # cond slice: placeholders at the Merges (+ invariant Enters)
        stops_c = {m: f"__c_{i}" for i, m in enumerate(carry_names)}
        stops_c.update({e: f"__ci_{i}" for i, e in enumerate(invariants)})
        cond_fn, cond_stop_order = self._frame_subgraph_callable(
            [loopcond.input[0]], stops_c, frame_nodes)
        # body slice: placeholders at the Switches' taken side (:1)
        stops_b = {switches[m].name if m in switches else m:
                   f"__b_{i}" for i, m in enumerate(carry_names)}
        stops_b.update({e: f"__bi_{i}" for i, e in enumerate(invariants)})
        body_fn, body_stop_order = self._frame_subgraph_callable(
            list(next_refs), stops_b, frame_nodes)

        n_carry = len(carry_names)
        n_total = n_carry + len(invariants)

        # stop-dict iteration order == insertion order == carries then
        # invariants, so positional zip in the callables lines up with the
        # init list below
        def cond(*args, key=None):
            return cond_fn(*args, key=key)[0]

        def body(*args, key=None):
            outs = body_fn(*args[:], key=key)
            return tuple(outs) + tuple(args[n_carry:])

        cond._accepts_rng = True
        body._accepts_rng = True

        init_refs = [self.node_by_name[e].input[0] for e in enter_of] + \
            [self.node_by_name[e].input[0] for e in invariants]
        outs = self.sd.while_loop(
            cond, body, *[self.sd.vars[self._ensure_var(r)]
                          for r in init_refs],
            name=f"{frame.replace('/', '_')}_while",
            max_iterations=self.while_max_iterations)
        outs = outs if isinstance(outs, tuple) else (outs,)
        for i, ex in enumerate(exit_nodes):
            if ex is not None:
                self._alias(ex.name, outs[i].name)
        self._frame_consumed |= frame_nodes
        # Dead-limb sweep: nested frames leave unreferenced frame ops
        # outside every slice (e.g. an inner loop-counter's Exit that
        # nothing consumes). Any frame op whose data inputs are all
        # consumed is part of the lowered region — absorb it, repeatedly.
        frame_op_kinds = ("Enter", "Exit", "NextIteration", "LoopCond",
                          "Merge", "Switch")
        changed = True
        while changed:
            changed = False
            for n in self.gd.node:
                if n.op not in frame_op_kinds or \
                        n.name in self._frame_consumed:
                    continue
                ins_ = [self._clean(i) for i in n.input]
                if ins_ and all(i in self._frame_consumed for i in ins_):
                    self._frame_consumed.add(n.name)
                    changed = True

    def _map_node(self, node) -> None:
        if node.name in self._frame_consumed:
            return
        op = node.op
        ins = self._inputs(node)
        sd = self.sd

        if op == "Const":
            return  # materialised lazily on first use
        if op in ("Placeholder", "PlaceholderWithDefault"):
            shape = self.input_shapes.get(node.name) or self._attr(node, "shape")
            if shape is not None:
                shape = tuple(None if s in (-1, 0) else s for s in shape)
            v = sd.placeholder(node.name, shape)
            if v.name != node.name:
                v.rename(node.name)
            return
        if op in ("Identity", "StopGradient", "PreventGradient", "CheckNumerics",
                  "NoOp", "IdentityN"):
            if not ins:
                return
            src = self._clean(ins[0])
            if src in self.const_values and src not in sd.vars:
                self.const_values[node.name] = self.const_values[src]
                return
            self._emit(node, "identity", [ins[0]])
            return
        if op == "VariableV2" or op == "VarHandleOp":
            raise ValueError("Graph contains un-frozen variables; freeze it first "
                             "(reference requires frozen graphs too)")

        simple = {
            "Add": "add", "AddV2": "add", "Sub": "sub", "Mul": "mul",
            "RealDiv": "div", "Div": "div", "Maximum": "maximum",
            "Minimum": "minimum", "Pow": "pow", "SquaredDifference": "squared_difference",
            "FloorDiv": "floordiv", "FloorMod": "mod",
            "Sqrt": "sqrt", "Rsqrt": "rsqrt", "Square": "square", "Exp": "exp",
            "Log": "log", "Log1p": "log1p", "Neg": "neg", "Abs": "abs", "Sign": "sign",
            "Floor": "floor", "Ceil": "ceil", "Round": "round", "Erf": "erf",
            "Tanh": "tanh", "Sigmoid": "sigmoid", "Relu": "relu", "Relu6": "relu6",
            "Elu": "elu", "Selu": "selu", "Softplus": "softplus", "Softsign": "softsign",
            "Sin": "sin", "Cos": "cos", "Tan": "tan",
            "Asin": "asin", "Acos": "acos", "Atan": "atan",
            "Sinh": "sinh", "Cosh": "cosh", "Atan2": "atan2",
            "Asinh": "asinh", "Acosh": "acosh", "Atanh": "atanh",
            "Expm1": "expm1", "Erfc": "erfc", "Lgamma": "gammaln",
            "Digamma": "digamma", "Rint": "rint", "Xlogy": "xlogy",
            "Xdivy": "xdivy", "DivNoNan": "div_no_nan",
            "MulNoNan": "multiply_no_nan", "TruncateDiv": "truncate_div",
            "TruncateMod": "truncate_mod", "Inv": "reciprocal",
            "InvertPermutation": "invert_permutation",
            "Cholesky": "cholesky",
            "MatrixDeterminant": "matrix_determinant",
            "Greater": "gt", "GreaterEqual": "gte", "Less": "lt", "LessEqual": "lte",
            "Equal": "eq", "NotEqual": "neq", "LogicalAnd": "logical_and",
            "LogicalOr": "logical_or", "LogicalNot": "logical_not",
            "Softmax": "softmax", "LogSoftmax": "log_softmax",
            "BiasAdd": "bias_add", "Reciprocal": "reciprocal",
            "ZerosLike": "zeros_like", "OnesLike": "ones_like",
            "L2Loss": "l2_loss", "Tile": None, "Select": "where", "SelectV2": "where",
        }
        if op in simple and simple[op]:
            self._emit(node, simple[op], ins)
            return

        if op == "MatMul":
            self._emit(node, "matmul", ins,
                       transpose_a=self._attr(node, "transpose_a", False),
                       transpose_b=self._attr(node, "transpose_b", False))
            return
        if op in ("BatchMatMul", "BatchMatMulV2", "BatchMatMulV3"):
            self._emit(node, "batch_matmul", ins,
                       transpose_a=self._attr(node, "adj_x", False),
                       transpose_b=self._attr(node, "adj_y", False))
            return
        if op == "Reshape":
            try:
                shape = self._const(ins[1]).astype(np.int64)
            except ValueError:
                # computed shape operand: defer to trace time — shape_of
                # chains stay concrete there, so the reshape is still
                # static for statically-shaped graphs
                self._emit(node, "reshape_dynamic", ins[:2])
                return
            self._emit(node, "reshape", ins[:1], shape=[int(s) for s in shape])
            return
        if op == "Transpose":
            perm = [int(p) for p in self._const(ins[1])]
            self._emit(node, "transpose", ins[:1], perm=perm)
            return
        if op == "ExpandDims":
            axis = int(self._const(ins[1]))
            self._emit(node, "expand_dims", ins[:1], axis=axis)
            return
        if op == "Squeeze":
            dims = self._attr(node, "squeeze_dims") or None
            self._emit(node, "squeeze", ins,
                       axis=tuple(dims) if dims else None)
            return
        if op in ("ConcatV2", "Concat"):
            if op == "ConcatV2":
                axis = int(self._const(ins[-1]))
                data = ins[:-1]
            else:
                axis = int(self._const(ins[0]))
                data = ins[1:]
            self._emit(node, "concat", data, axis=axis)
            return
        if op == "Pack":
            self._emit(node, "stack", ins, axis=self._attr(node, "axis", 0))
            return
        if op == "Unpack":
            n = self._attr(node, "num")
            vars_ = [sd.vars[self._ensure_var(ins[0])]]
            outs = sd._apply("unstack", vars_,
                             attrs={"axis": self._attr(node, "axis", 0), "num": n},
                             name=node.name, n_outputs=n)
            outs = outs if isinstance(outs, tuple) else (outs,)
            for i, o in enumerate(outs):
                want = node.name if i == 0 else f"{node.name}:{i}"
                if o.name != want:
                    o.rename(want)
            return
        if op == "Split":
            n = self._attr(node, "num_split")
            axis = int(self._const(ins[0]))
            vars_ = [sd.vars[self._ensure_var(ins[1])]]
            outs = sd._apply("split", vars_, attrs={"num_splits": n, "axis": axis},
                             name=node.name, n_outputs=n)
            outs = outs if isinstance(outs, tuple) else (outs,)
            for i, o in enumerate(outs):
                want = node.name if i == 0 else f"{node.name}:{i}"
                if o.name != want:
                    o.rename(want)
            return
        if op == "Tile":
            mult = [int(m) for m in self._const(ins[1])]
            self._emit(node, "tile", ins[:1], multiples=mult)
            return
        if op == "Slice":
            begin = [int(b) for b in self._const(ins[1])]
            size = [int(s) for s in self._const(ins[2])]
            self._emit(node, "slice", ins[:1], begin=begin, size=size)
            return
        if op == "StridedSlice":
            self._emit(node, "strided_slice", ins[:1],
                       begin=[int(b) for b in self._const(ins[1])],
                       end=[int(e) for e in self._const(ins[2])],
                       strides=[int(s) for s in self._const(ins[3])],
                       begin_mask=self._attr(node, "begin_mask", 0),
                       end_mask=self._attr(node, "end_mask", 0),
                       shrink_axis_mask=self._attr(node, "shrink_axis_mask", 0),
                       new_axis_mask=self._attr(node, "new_axis_mask", 0),
                       ellipsis_mask=self._attr(node, "ellipsis_mask", 0))
            return
        if op in ("GatherV2", "Gather"):
            axis = int(self._const(ins[2])) if len(ins) > 2 else 0
            self._emit(node, "gather", ins[:2], axis=axis)
            return
        if op == "GatherNd":
            self._emit(node, "gather_nd", ins[:2])
            return
        if op == "OneHot":
            depth = int(self._const(ins[1]))
            on = float(self._const(ins[2])) if len(ins) > 2 else 1.0
            off = float(self._const(ins[3])) if len(ins) > 3 else 0.0
            self._emit(node, "one_hot", ins[:1], depth=depth, on_value=on,
                       off_value=off, axis=self._attr(node, "axis", -1))
            return
        if op == "Cast":
            self._emit(node, "cast", ins, dtype=_np_dtype(self._attr(node, "DstT")))
            return
        if op in ("Mean", "Sum", "Max", "Min", "Prod"):
            axis = self._const(ins[1])
            axis = [int(a) for a in np.atleast_1d(axis)]
            red = {"Mean": "reduce_mean", "Sum": "reduce_sum", "Max": "reduce_max",
                   "Min": "reduce_min", "Prod": "reduce_prod"}[op]
            self._emit(node, red, ins[:1], axis=axis,
                       keepdims=self._attr(node, "keep_dims", False))
            return
        if op in ("ArgMax", "ArgMin"):
            axis = int(self._const(ins[1])) if len(ins) > 1 else -1
            self._emit(node, "argmax" if op == "ArgMax" else "argmin", ins[:1], axis=axis)
            return
        if op == "Pad" or op == "PadV2":
            pads = [[int(a), int(b)] for a, b in self._const(ins[1])]
            cv = float(self._const(ins[2])) if op == "PadV2" else 0.0
            self._emit(node, "pad", ins[:1], paddings=pads, constant_value=cv)
            return
        if op == "Shape":
            # static fold if the producer's shape is known at import time
            self._emit(node, "shape_of", ins[:1])
            return
        if op == "Fill":
            shape = [int(s) for s in self._const(ins[0])]
            value = float(self._const(ins[1]))
            arr = np.full(shape, value, np.float32)
            self.const_values[node.name] = arr
            return
        if op == "Range":
            start, limit, delta = (self._const(i) for i in ins[:3])
            self.const_values[node.name] = np.arange(start, limit, delta)
            return
        if op == "Conv2D":
            strides = self._attr(node, "strides", [1, 1, 1, 1])
            dil = self._attr(node, "dilations", [1, 1, 1, 1])
            self._emit(node, "conv2d", ins[:2],
                       stride=[int(strides[1]), int(strides[2])],
                       padding=self._attr(node, "padding", "SAME"),
                       dilation=[int(dil[1]), int(dil[2])])
            return
        if op == "DepthwiseConv2dNative":
            strides = self._attr(node, "strides", [1, 1, 1, 1])
            dil = self._attr(node, "dilations", [1, 1, 1, 1])
            if any(int(d) != 1 for d in dil):
                raise NotImplementedError(
                    f"DepthwiseConv2dNative {node.name!r} with dilation {dil}")
            self._emit(node, "depthwise_conv2d", ins[:2],
                       stride=[int(strides[1]), int(strides[2])],
                       padding=self._attr(node, "padding", "SAME"))
            return
        if op == "Conv2DBackpropInput":
            # (output_sizes, filter, out_backprop) -> deconvolution; Keras
            # Conv2DTranspose layers export as this op
            dil = self._attr(node, "dilations", [1, 1, 1, 1])
            if any(int(d) != 1 for d in dil):
                raise NotImplementedError(
                    f"Conv2DBackpropInput {node.name!r} with dilation {dil}")
            try:
                out_shape = [int(s) for s in self._const(ins[0])]
            except ValueError:
                out_shape = None  # computed sizes: registry op validates shape
            strides = self._attr(node, "strides", [1, 1, 1, 1])
            self._emit(node, "conv2d_transpose", [ins[2], ins[1]],
                       stride=[int(strides[1]), int(strides[2])],
                       padding=self._attr(node, "padding", "SAME"),
                       output_shape=out_shape)
            return
        if op == "Einsum":
            self._emit(node, "einsum", ins,
                       equation=self._attr(node, "equation"))
            return
        if op == "LeakyRelu":
            self._emit(node, "leaky_relu", ins,
                       alpha=self._attr(node, "alpha", 0.2))
            return
        if op in ("Cumsum", "Cumprod"):
            if self._attr(node, "exclusive", False) \
                    or self._attr(node, "reverse", False):
                raise NotImplementedError(
                    f"{op} {node.name!r} with exclusive/reverse")
            axis = int(self._const(ins[1]))
            self._emit(node, op.lower(), ins[:1], axis=axis)
            return
        if op in ("DepthToSpace", "SpaceToDepth"):
            if self._attr(node, "data_format", "NHWC") != "NHWC":
                raise NotImplementedError(
                    f"{op} {node.name!r} with data_format != NHWC")
            self._emit(node,
                       "depth_to_space" if op == "DepthToSpace"
                       else "space_to_depth",
                       ins[:1], block_size=self._attr(node, "block_size", 2))
            return
        if op == "MatrixBandPart":
            self._emit(node, "matrix_band_part", ins[:1],
                       num_lower=int(self._const(ins[1])),
                       num_upper=int(self._const(ins[2])))
            return
        if op in ("MatrixDiag", "MatrixDiagV2", "MatrixDiagV3"):
            if len(ins) > 1:  # V2/V3 carry (k, num_rows, num_cols, padding)
                k = int(np.atleast_1d(self._const(ins[1]))[0])
                extras = [int(np.atleast_1d(self._const(i))[0])
                          for i in ins[2:4] if i]
                if k != 0 or any(e not in (-1,) for e in extras):
                    raise NotImplementedError(
                        f"{op} {node.name!r} with k={k}/explicit dims")
            self._emit(node, "matrix_diag", ins[:1])
            return
        if op in ("MatrixDiagPart", "MatrixDiagPartV2", "MatrixDiagPartV3"):
            if len(ins) > 1:
                k = int(np.atleast_1d(self._const(ins[1]))[0])
                if k != 0:
                    raise NotImplementedError(
                        f"{op} {node.name!r} with k={k}")
            self._emit(node, "matrix_diag_part", ins[:1])
            return
        if op == "MatrixInverse":
            if self._attr(node, "adjoint", False):
                raise NotImplementedError(
                    f"MatrixInverse {node.name!r} with adjoint=True")
            self._emit(node, "matrix_inverse", ins[:1])
            return
        if op == "ReverseV2":
            axes = [int(a) for a in np.atleast_1d(self._const(ins[1]))]
            self._emit(node, "reverse", ins[:1], axis=axes)
            return
        if op == "TopKV2":
            k = int(self._const(ins[1]))
            vars_ = [sd.vars[self._ensure_var(ins[0])]]
            outs = sd._apply("top_k", vars_, attrs={"k": k},
                             name=node.name, n_outputs=2)
            self._name_outputs(node, outs if isinstance(outs, tuple) else (outs,))
            return
        if op == "AddN":
            self._emit(node, "add_n", ins)
            return
        if op in ("ResizeBilinear", "ResizeNearestNeighbor"):
            size = [int(s) for s in self._const(ins[1])]
            if self._attr(node, "align_corners", False):
                raise NotImplementedError(
                    f"{op} {node.name!r} with align_corners=True; re-export "
                    "with tf.image.resize (half-pixel centers)")
            if op == "ResizeBilinear" and not self._attr(
                    node, "half_pixel_centers", False):
                raise NotImplementedError(
                    f"ResizeBilinear {node.name!r} uses the legacy TF1 "
                    "corner-aligned-origin sampling (half_pixel_centers="
                    "False); re-export with tf.image.resize")
            if op == "ResizeBilinear":
                self._emit(node, "resize_bilinear", ins[:1],
                           height=size[0], width=size[1])
            else:
                self._emit(node, "resize_nearest", ins[:1],
                           height=size[0], width=size[1],
                           half_pixel_centers=self._attr(
                               node, "half_pixel_centers", False))
            return
        if op in ("MaxPool", "AvgPool"):
            k = self._attr(node, "ksize", [1, 2, 2, 1])
            s = self._attr(node, "strides", [1, 2, 2, 1])
            self._emit(node, "max_pool2d" if op == "MaxPool" else "avg_pool2d",
                       ins[:1], kernel=[int(k[1]), int(k[2])],
                       stride=[int(s[1]), int(s[2])],
                       padding=self._attr(node, "padding", "VALID"))
            return
        if op in ("RandomUniform", "RandomStandardNormal", "TruncatedNormal",
                  "StatelessRandomUniform", "StatelessRandomUniformV2",
                  "StatelessRandomNormal", "StatelessRandomNormalV2",
                  "StatelessTruncatedNormal", "StatelessTruncatedNormalV2"):
            # Stochastic nodes (Keras training=True dropout exports these):
            # the static `seed` names the stream; sd.fit's executor folds a
            # per-step key into it so draws are fresh every training
            # iteration (reference: stateful NativeRandom redraws per step).
            reg = {"RandomUniform": "random_uniform",
                   "StatelessRandomUniform": "random_uniform",
                   "StatelessRandomUniformV2": "random_uniform",
                   "RandomStandardNormal": "random_normal",
                   "StatelessRandomNormal": "random_normal",
                   "StatelessRandomNormalV2": "random_normal",
                   "TruncatedNormal": "truncated_normal",
                   "StatelessTruncatedNormal": "truncated_normal",
                   "StatelessTruncatedNormalV2": "truncated_normal"}[op]
            try:
                shape = [int(s) for s in self._const(ins[0])]
                self._emit(node, reg, [], shape=shape, seed=self._tf_seed(node))
            except ValueError:
                # computed shape (tf.shape(x), the Keras dropout form): the
                # shape_of chain stays concrete at trace time, so the draw
                # is still statically shaped
                self._emit(node, reg, ins[:1], seed=self._tf_seed(node))
            return
        if op == "Multinomial":
            num = int(self._const(ins[1]))
            self._emit(node, "random_categorical", ins[:1],
                       num_samples=num, seed=self._tf_seed(node))
            return
        if op in ("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3"):
            # inference form: (x, gamma, beta, mean, var)
            x, gamma, beta, mean, var = ins[:5]
            self._emit(node, "batch_norm", [x, mean, var, gamma, beta],
                       eps=self._attr(node, "epsilon", 1e-3))
            return

        # ---- TF1-style lowered conditionals (Switch/Merge dataflow) ----
        # Merge lowers onto sd.cond (lax.cond): each branch is backward-
        # sliced into a standalone subgraph and only the taken one
        # executes, matching the reference's lazy Switch/Merge frame
        # semantics. Unmatched Merges fall back to execute-both + select
        # (the graph is pure, so that form is numerically exact).
        if op == "Switch":
            # outputs: :0 = false branch, :1 = true branch; both carry data
            data_v = sd.vars[self._ensure_var(ins[0])]
            self._switch_pred[node.name] = ins[1]
            o0 = sd._apply("identity", [data_v], name=node.name)
            if o0.name != node.name:
                o0.rename(node.name)
            o1 = sd._apply("identity", [data_v], name=f"{node.name}:1")
            if o1.name != f"{node.name}:1":
                o1.rename(f"{node.name}:1")
            return
        if op == "Merge":
            picks = [self._controlling_switch(i) for i in ins]
            true_refs = [r for r, p in zip(ins, picks) if p and p[1] == 1]
            false_refs = [r for r, p in zip(ins, picks) if p and p[1] == 0]
            if not true_refs or not false_refs:
                raise NotImplementedError(
                    f"Merge {node.name!r}: cannot associate its inputs with "
                    "a controlling Switch true/false pair. TF1 while-loop "
                    "frames ARE supported (Enter-rooted frames lower onto "
                    "sd.while_loop); this Merge is outside any frame and "
                    "has no matched Switch — likely a malformed or "
                    "hand-edited frozen graph")
            pred_ref = self._switch_pred[next(p for p in picks if p)[0]]
            lowered = False
            if self.lazy_conditionals:
                try:
                    # lazy branch-select: only the taken branch executes
                    self._lower_cond_merge(node, true_refs[0], false_refs[0],
                                           pred_ref)
                    lowered = True
                # The where-form is numerically exact, so ANY failure to
                # build the lazy slice falls back rather than failing the
                # import: NotImplementedError from the slice machinery,
                # ValueError from a branch op that static-folds a Const
                # the slice turned into a Placeholder, RecursionError from
                # a pathologically deep branch topo-sort.
                except (NotImplementedError, ValueError, RecursionError):
                    pass
            if not lowered:
                # execute-both + select fallback (numerically identical,
                # up to 2x the work of the taken branch)
                pred_v = sd.vars[self._ensure_var(pred_ref)]
                tv = sd.vars[self._ensure_var(true_refs[0])]
                fv = sd.vars[self._ensure_var(false_refs[0])]
                out = sd._apply("where", [pred_v, tv, fv], name=node.name)
                if out.name != node.name:
                    out.rename(node.name)
            # second output (value_index) is rarely consumed; emit if needed
            return
        if op == "Enter":
            # First frame op in topo order: lower the WHOLE frame now
            # (reference: TFGraphMapper maps Enter/Exit/NextIteration/
            # LoopCond frames into SameDiff's loop frames; here the frame
            # is reconstructed and lowered onto sd.while_loop -> XLA's
            # structured lax.while_loop)
            self._lower_tf1_frame(self._attr(node, "frame_name"))
            return
        if op in ("Exit", "NextIteration", "LoopCond"):
            raise NotImplementedError(
                f"Orphan TF1 frame op {op!r} (node {node.name!r}) with no "
                "Enter — malformed frozen graph")

        # ---- TF2 function graphs + structured control flow ----
        if op in ("PartitionedCall", "StatefulPartitionedCall"):
            self._inline_call(node, node.attr["f"].func.name, ins)
            return
        if op in ("While", "StatelessWhile"):
            cond_f = self._function_callable(node.attr["cond"].func.name)
            body_f = self._function_callable(node.attr["body"].func.name)
            n = len(ins)
            vars_ = [sd.vars[self._ensure_var(i)] for i in ins]

            def cond_w(*c, key=None):
                return cond_f(*c, key=key)[0]

            def body_w(*c, key=None):
                return tuple(body_f(*c, key=key))

            # keep the per-step rng threading through the wrappers (dropout
            # inside a While body stays live during sd.fit)
            cond_w._accepts_rng = True
            body_w._accepts_rng = True
            outs = sd.while_loop(
                cond_w, body_w,
                *vars_, name=node.name,
                max_iterations=self.while_max_iterations)
            outs = outs if isinstance(outs, tuple) else (outs,)
            self._name_outputs(node, outs)
            return
        if op in ("If", "StatelessIf"):
            then_f = self._function_callable(node.attr["then_branch"].func.name)
            else_f = self._function_callable(node.attr["else_branch"].func.name)
            nout = len(node.attr["Tout"].list.type) or 1
            pred_v = sd.vars[self._ensure_var(ins[0])]
            arg_vs = [sd.vars[self._ensure_var(i)] for i in ins[1:]]
            if nout == 1:
                def tf_fn(*xs, key=None):
                    return then_f(*xs, key=key)[0]

                def ef_fn(*xs, key=None):
                    return else_f(*xs, key=key)[0]
            else:
                def tf_fn(*xs, key=None):
                    return tuple(then_f(*xs, key=key))

                def ef_fn(*xs, key=None):
                    return tuple(else_f(*xs, key=key))
            tf_fn._accepts_rng = True
            ef_fn._accepts_rng = True
            outs = sd.cond(pred_v, tf_fn, ef_fn, *arg_vs, name=node.name,
                           n_outputs=nout)
            outs = outs if isinstance(outs, tuple) else (outs,)
            self._name_outputs(node, outs)
            return

        raise NotImplementedError(
            f"TF op {op!r} (node {node.name!r}) is not mapped; "
            f"extend deeplearning4j_tpu/imports/tf_import.py")


def _np_dtype(tf_name: str) -> str:
    return {"float": "float32", "double": "float64", "int32": "int32",
            "int64": "int32", "bool": "bool", "half": "float16",
            "bfloat16": "bfloat16"}.get(tf_name, tf_name or "float32")
