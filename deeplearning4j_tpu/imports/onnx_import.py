"""ONNX model import.

Rebuild of upstream ``org.nd4j.imports.graphmapper.onnx.OnnxGraphMapper``
(partial in the reference — SURVEY.md §2.2): parse a ``ModelProto`` with the
in-repo wire decoder (``onnx_proto.py``; no ``onnx`` package offline), then
map each node onto the SameDiff graph through the op registry.

Covers the common inference op set (conv/pool/gemm/matmul, batchnorm,
activations, reshape family, reductions, elementwise) — a superset of what
the reference's partial mapper handled. ONNX is NCHW; compute ops here are
NHWC (TPU-native), so convs/pools transpose in and out — XLA cancels
adjacent transposes, so imported graphs stay fusion-friendly.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.autodiff.samediff import SameDiff
from deeplearning4j_tpu.imports import onnx_proto


class OnnxGraphMapper:
    @staticmethod
    def import_graph(path_or_bytes,
                     input_shapes: Optional[Dict[str, tuple]] = None) -> SameDiff:
        model = onnx_proto.load_model(path_or_bytes)
        return _OnnxImporter(model["graph"], input_shapes or {}).run()


# ONNX AttributeProto.type -> dict field holding the value
_ATTR_FIELDS = {1: "f", 2: "i", 3: "s", 4: "t", 6: "floats", 7: "ints",
                8: "strings"}


def _attrs(node: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for a in node.get("attribute", []):
        field = _ATTR_FIELDS.get(a.get("type"))
        val = a.get(field) if field else None
        if val is None:  # fall back to whichever field is populated
            for f in ("i", "f", "s", "t", "ints", "floats", "strings"):
                if f in a:
                    val = a[f]
                    break
        if isinstance(val, bytes):
            val = val.decode("utf-8", "replace")
        out[a["name"]] = val
    return out


def _fold_slice(a):
    """numpy Slice over constants: data, starts, ends[, axes[, steps]]."""
    data, starts, ends = a[0], a[1], a[2]
    axes = a[3] if len(a) > 3 else np.arange(len(starts))
    steps = a[4] if len(a) > 4 else np.ones(len(starts), np.int64)
    idx = [slice(None)] * data.ndim
    for st, en, ax, sp in zip(starts, ends, axes, steps):
        idx[int(ax)] = slice(int(st), int(en), int(sp))
    return data[tuple(idx)]


class _OnnxImporter:
    def __init__(self, graph: Dict[str, Any], input_shapes: Dict[str, tuple]):
        self.g = graph
        self.sd = SameDiff.create()
        self.const_values: Dict[str, np.ndarray] = {}
        self.rank: Dict[str, int] = {}
        self.input_shapes = input_shapes

    # ------------------------------------------------------------- plumbing
    def _ensure_var(self, name: str) -> Any:
        if name in self.sd.vars:
            return self.sd.vars[name]
        if name in self.const_values:
            v = self.sd.constant(name, self.const_values[name])
            return v
        raise KeyError(f"ONNX input {name!r} not found (not a node output, "
                       "graph input, or initializer)")

    def _emit(self, op: str, inputs: List[Any], out_name: str, **attrs) -> Any:
        vars_ = [self._ensure_var(i) if isinstance(i, str) else i for i in inputs]
        return self._name_as(
            self.sd._apply(op, vars_, attrs=attrs or None, name=out_name),
            out_name)

    @staticmethod
    def _name_as(var, out_name: str):
        if var.name != out_name:
            var.rename(out_name)
        return var

    def _const_of(self, name: str) -> np.ndarray:
        if name in self.const_values:
            return self.const_values[name]
        raise ValueError(f"expected static initializer for {name!r}")

    # ------------------------------------------------------------------ run
    def run(self) -> SameDiff:
        for init in self.g.get("initializer", []):
            arr = onnx_proto.tensor_to_numpy(init)
            self.const_values[init["name"]] = arr
            self.rank[init["name"]] = arr.ndim
        init_names = set(self.const_values)
        for vi in self.g.get("input", []):
            name = vi["name"]
            if name in init_names:
                continue
            shape = self.input_shapes.get(name) or self._vi_shape(vi)
            self.sd.placeholder(name, shape=tuple(shape) if shape else None)
            if shape:
                self.rank[name] = len(shape)
        for node in self.g.get("node", []):
            self._map_node(node)
        return self.sd

    @staticmethod
    def _vi_shape(vi: Dict[str, Any]) -> Optional[tuple]:
        try:
            dims = vi["type"]["tensor_type"]["shape"]["dim"]
            shape = tuple(d.get("dim_value", 1) for d in dims)
            return shape if all(s > 0 for s in shape) else None
        except KeyError:
            return None

    # ------------------------------------------------------ constant folding
    # Shape-carrying values (pads/axes/shapes) often arrive through small
    # Cast/Concat/Slice subgraphs over constants; fold those to numpy at
    # import time so downstream attrs stay static (the TF importer and the
    # reference's initFromTensorFlow do the same).
    _FOLD = {
        "Cast": lambda a, ins, attrs: a[0].astype(
            onnx_proto._DTYPES.get(attrs.get("to", 1), np.float32)),
        "Concat": lambda a, ins, attrs: np.concatenate(a, axis=attrs.get("axis", 0)),
        "Unsqueeze": lambda a, ins, attrs: np.expand_dims(
            a[0], tuple(int(x) for x in (a[1] if len(a) > 1 else attrs.get("axes", (0,))))),
        "Squeeze": lambda a, ins, attrs: np.squeeze(
            a[0], tuple(int(x) for x in (a[1] if len(a) > 1 else attrs.get("axes", ()))) or None),
        "Reshape": lambda a, ins, attrs: a[0].reshape(tuple(int(x) for x in a[1])),
        "Transpose": lambda a, ins, attrs: np.transpose(a[0], attrs.get("perm")),
        "Gather": lambda a, ins, attrs: np.take(a[0], a[1].astype(np.int64),
                                                axis=attrs.get("axis", 0)),
        "Identity": lambda a, ins, attrs: a[0],
        "Add": lambda a, ins, attrs: a[0] + a[1],
        "Sub": lambda a, ins, attrs: a[0] - a[1],
        "Mul": lambda a, ins, attrs: a[0] * a[1],
        "Div": lambda a, ins, attrs: a[0] // a[1]
            if np.issubdtype(a[0].dtype, np.integer) else a[0] / a[1],
        "Slice": lambda a, ins, attrs: _fold_slice(a),
        "Range": lambda a, ins, attrs: np.arange(
            a[0].ravel()[0], a[1].ravel()[0], a[2].ravel()[0]),
    }

    def _try_fold(self, node: Dict[str, Any]) -> bool:
        op = node.get("op_type", "")
        fn = self._FOLD.get(op)
        ins = [i for i in node.get("input", []) if i]
        if fn is None or not ins or not all(i in self.const_values for i in ins):
            return False
        args = [np.asarray(self.const_values[i]) for i in ins]
        try:
            val = fn(args, ins, _attrs(node))
        except Exception:
            return False
        out = node["output"][0]
        self.const_values[out] = np.asarray(val)
        self.rank[out] = self.const_values[out].ndim
        return True

    # ---------------------------------------------------------- op mappings
    def _map_node(self, node: Dict[str, Any]) -> None:
        op = node.get("op_type", "")
        if op not in ("Constant", "ConstantOfShape") and self._try_fold(node):
            return
        # ONNX marks omitted optional inputs with "": keep slots positional
        ins: List[str] = list(node.get("input", []))
        outs: List[str] = node.get("output", [])
        out = outs[0]
        a = _attrs(node)
        sd = self.sd

        def rank_of(name: str, default: int = 4) -> int:
            return self.rank.get(name, default)

        def setr(r: int, name: str = out) -> None:
            self.rank[name] = r

        simple = {
            "Relu": "relu", "Sigmoid": "sigmoid", "Tanh": "tanh",
            "Exp": "exp", "Log": "log", "Neg": "neg", "Abs": "abs",
            "Sqrt": "sqrt", "Erf": "erf", "Floor": "floor", "Ceil": "ceil",
            "Sign": "sign", "Softplus": "softplus", "Softsign": "softsign",
            "Reciprocal": "reciprocal", "Sin": "sin", "Cos": "cos",
            "Not": "logical_not", "Identity": "identity",
        }
        binary = {"Add": "add", "Sub": "sub", "Mul": "mul", "Div": "div",
                  "Pow": "pow", "Greater": "gt", "Less": "lt", "Equal": "eq",
                  "And": "logical_and", "Or": "logical_or",
                  "Max": "maximum", "Min": "minimum"}

        if op in simple:
            self._emit(simple[op], [ins[0]], out)
            setr(rank_of(ins[0]))
        elif op in binary and len(ins) == 2:
            self._emit(binary[op], ins, out)
            setr(max(rank_of(ins[0]), rank_of(ins[1])))
        elif op == "Sum":
            acc = self._ensure_var(ins[0])
            for extra in [i for i in ins[1:] if i]:
                acc = sd._apply("add", [acc, self._ensure_var(extra)])
            self._name_as(acc, out)
            setr(rank_of(ins[0]))
        elif op == "Constant":
            val = a.get("value")
            arr = (onnx_proto.tensor_to_numpy(val) if isinstance(val, dict)
                   else np.asarray(val))
            self.const_values[out] = arr
            setr(arr.ndim)
        elif op == "ConstantOfShape":
            shape = tuple(int(s) for s in self._const_of(ins[0]))
            val = a.get("value")
            fill = (onnx_proto.tensor_to_numpy(val).ravel()[0]
                    if isinstance(val, dict) else 0.0)
            self.const_values[out] = np.full(shape, fill)
            setr(len(shape))
        elif op == "LeakyRelu":
            self._emit("leaky_relu", [ins[0]], out, alpha=a.get("alpha", 0.01))
            setr(rank_of(ins[0]))
        elif op == "Elu":
            self._emit("elu", [ins[0]], out)
            setr(rank_of(ins[0]))
        elif op == "Selu":
            self._emit("selu", [ins[0]], out)
            setr(rank_of(ins[0]))
        elif op == "Clip":
            lo = (float(self._const_of(ins[1]).ravel()[0])
                  if len(ins) > 1 and ins[1] else a.get("min", -np.inf))
            hi = (float(self._const_of(ins[2]).ravel()[0])
                  if len(ins) > 2 and ins[2] else a.get("max", np.inf))
            self._emit("clip_by_value", [ins[0]], out, lo=lo, hi=hi)
            setr(rank_of(ins[0]))
        elif op in ("Softmax", "LogSoftmax"):
            axis = a.get("axis", -1)
            self._emit("softmax" if op == "Softmax" else "log_softmax",
                       [ins[0]], out, axis=axis)
            setr(rank_of(ins[0]))
        elif op == "Gelu":
            self._emit("gelu", [ins[0]], out)
            setr(rank_of(ins[0]))
        elif op == "MatMul":
            self._emit("matmul", ins, out)
            setr(max(rank_of(ins[0], 2), rank_of(ins[1], 2)))
        elif op == "Gemm":
            self._map_gemm(ins, out, a)
        elif op == "Conv":
            self._map_conv(ins, out, a)
        elif op in ("MaxPool", "AveragePool"):
            self._map_pool(op, ins, out, a)
        elif op in ("GlobalAveragePool", "GlobalMaxPool"):
            red = "reduce_mean" if op == "GlobalAveragePool" else "reduce_max"
            self._emit(red, [ins[0]], out, axis=(2, 3), keepdims=True)
            setr(4)
        elif op == "BatchNormalization":
            self._map_batchnorm(ins, out, a)
        elif op == "LayerNormalization":
            axis = a.get("axis", -1)
            args = [ins[0], ins[1]] + ([ins[2]] if len(ins) > 2 and ins[2] else [])
            self._emit("layer_norm", args, out, axis=axis,
                       eps=a.get("epsilon", 1e-5))
            setr(rank_of(ins[0]))
        elif op == "Flatten":
            self._emit("flatten2d", [ins[0]], out, axis=a.get("axis", 1))
            setr(2)
        elif op == "Reshape":
            shape = tuple(int(s) for s in self._const_of(ins[1]))
            self._emit("reshape", [ins[0]], out, shape=shape)
            setr(len(shape))
        elif op == "Transpose":
            perm = tuple(a.get("perm") or reversed(range(rank_of(ins[0]))))
            self._emit("transpose", [ins[0]], out, perm=perm)
            setr(len(perm))
        elif op == "Concat":
            vars_ = [self._ensure_var(i) for i in ins]
            self._name_as(sd._apply("concat", vars_,
                                    attrs={"axis": a.get("axis", 0)},
                                    name=out), out)
            setr(rank_of(ins[0]))
        elif op == "Squeeze":
            axes = (tuple(int(s) for s in self._const_of(ins[1]))
                    if len(ins) > 1 else tuple(a.get("axes", ())))
            self._emit("squeeze", [ins[0]], out, axis=axes or None)
            setr(rank_of(ins[0]) - max(1, len(axes)))
        elif op == "Unsqueeze":
            axes = (tuple(int(s) for s in self._const_of(ins[1]))
                    if len(ins) > 1 else tuple(a.get("axes", ())))
            v = self._ensure_var(ins[0])
            for ax in sorted(axes):
                v = sd._apply("expand_dims", [v], attrs={"axis": int(ax)})
            self._name_as(v, out)
            setr(rank_of(ins[0]) + len(axes))
        elif op == "Gather":
            self._emit("gather", [ins[0], ins[1]], out, axis=a.get("axis", 0))
            setr(rank_of(ins[0]) + rank_of(ins[1], 1) - 1)
        elif op == "Slice":
            self._map_slice(ins, out, a)
        elif op in ("ReduceMean", "ReduceSum", "ReduceMax", "ReduceMin",
                    "ReduceProd"):
            axes = (tuple(int(s) for s in self._const_of(ins[1]))
                    if len(ins) > 1 else tuple(a.get("axes", ())))
            keep = bool(a.get("keepdims", 1))
            red = {"ReduceMean": "reduce_mean", "ReduceSum": "reduce_sum",
                   "ReduceMax": "reduce_max", "ReduceMin": "reduce_min",
                   "ReduceProd": "reduce_prod"}[op]
            self._emit(red, [ins[0]], out, axis=axes or None, keepdims=keep)
            setr(rank_of(ins[0]) if keep else rank_of(ins[0]) - max(1, len(axes)))
        elif op == "Cast":
            self._emit("cast", [ins[0]], out,
                       dtype=str(onnx_proto._DTYPES[a.get("to", 1)].__name__))
            setr(rank_of(ins[0]))
        elif op == "Dropout":
            self._emit("identity", [ins[0]], out)  # inference-mode import
            setr(rank_of(ins[0]))
        elif op == "Shape":
            # static by construction (importer resolves to a constant)
            src = ins[0]
            if src in self.const_values:
                self.const_values[out] = np.asarray(
                    self.const_values[src].shape, np.int64)
            else:
                self._emit("shape_of", [src], out)
            setr(1)
        elif op == "Where":
            self._emit("where", ins, out)
            setr(max(rank_of(i) for i in ins))
        elif op == "Tile":
            reps = tuple(int(r) for r in self._const_of(ins[1]))
            self._emit("tile", [ins[0]], out, multiples=reps)
            setr(rank_of(ins[0]))
        elif op == "Pad":
            pads = (tuple(int(p) for p in self._const_of(ins[1]))
                    if len(ins) > 1 else tuple(a.get("pads", ())))
            r = len(pads) // 2
            pairs = tuple((pads[i], pads[i + r]) for i in range(r))
            mode = {"constant": "constant", "reflect": "reflect",
                    "edge": "edge", "wrap": "wrap"}[a.get("mode", "constant")]
            self._emit("pad", [ins[0]], out, paddings=pairs, mode=mode)
            setr(rank_of(ins[0]))
        elif op == "ArgMax":
            axis = a.get("axis", 0)
            if a.get("keepdims", 1):
                v = sd._apply("argmax", [self._ensure_var(ins[0])],
                              attrs={"axis": axis})
                self._name_as(sd._apply("expand_dims", [v],
                                        attrs={"axis": axis}), out)
                setr(rank_of(ins[0]))
            else:
                self._emit("argmax", [ins[0]], out, axis=axis)
                setr(max(1, rank_of(ins[0]) - 1))
        else:
            raise NotImplementedError(
                f"ONNX op {op!r} not mapped (node {node.get('name')!r})")

    # --------------------------------------------------------- composite ops
    def _map_gemm(self, ins, out, a):
        sd = self.sd
        x = self._ensure_var(ins[0])
        w = self._ensure_var(ins[1])
        if a.get("transA"):
            x = sd._apply("transpose", [x], attrs={"perm": (1, 0)})
        if a.get("transB"):
            w = sd._apply("transpose", [w], attrs={"perm": (1, 0)})
        y = sd._apply("matmul", [x, w])
        alpha, beta = a.get("alpha", 1.0), a.get("beta", 1.0)
        if alpha != 1.0:
            y = sd._apply("mul", [y, sd.constant(np.float32(alpha))])
        if len(ins) > 2 and ins[2]:
            b = self._ensure_var(ins[2])
            if beta != 1.0:
                b = sd._apply("mul", [b, sd.constant(np.float32(beta))])
            y = sd._apply("add", [y, b])
        self._name_as(y, out)
        self.rank[out] = 2

    def _conv_padding(self, a):
        auto = a.get("auto_pad", "NOTSET") or "NOTSET"
        if auto == "SAME_UPPER":
            return "SAME"
        if auto == "SAME_LOWER":
            return "SAME_LOWER"  # XLA convs take it; pools reject it below
        pads = a.get("pads")
        if not pads:
            return "VALID"
        r = len(pads) // 2
        return tuple((int(pads[i]), int(pads[i + r])) for i in range(r))

    def _map_conv(self, ins, out, a):
        sd = self.sd
        w = self._const_of(ins[1])  # OIHW
        groups = int(a.get("group", 1))
        if w.ndim != 4:
            raise NotImplementedError("only 2-D Conv is mapped")
        w_hwio = np.transpose(w, (2, 3, 1, 0))  # -> HWIO (I = C_in/groups)
        x = sd._apply("transpose", [self._ensure_var(ins[0])],
                      attrs={"perm": (0, 2, 3, 1)})
        stride = tuple(a.get("strides") or (1, 1))
        dilation = tuple(a.get("dilations") or (1, 1))
        pad = self._conv_padding(a)
        args = [x, sd.constant(w_hwio)]
        if len(ins) > 2 and ins[2]:
            args.append(self._ensure_var(ins[2]))
        y = sd._apply("conv2d", args,
                      attrs={"stride": stride, "padding": pad,
                             "dilation": dilation,
                             **({"groups": groups} if groups != 1 else {})})
        self._name_as(sd._apply("transpose", [y],
                                attrs={"perm": (0, 3, 1, 2)}, name=out), out)
        self.rank[out] = 4

    def _map_pool(self, op, ins, out, a):
        sd = self.sd
        kernel = tuple(a.get("kernel_shape") or (2, 2))
        stride = tuple(a.get("strides") or kernel)
        pad = self._conv_padding(a)
        if pad == "SAME_LOWER":
            raise NotImplementedError("auto_pad=SAME_LOWER on pooling")
        if isinstance(pad, tuple):  # reduce_window pads every dim
            pad = ((0, 0), *pad, (0, 0))
        x = sd._apply("transpose", [self._ensure_var(ins[0])],
                      attrs={"perm": (0, 2, 3, 1)})
        extra = ({"count_include_pad": True}
                 if op == "AveragePool" and a.get("count_include_pad") else {})
        y = sd._apply("max_pool2d" if op == "MaxPool" else "avg_pool2d", [x],
                      attrs={"kernel": kernel, "stride": stride,
                             "padding": pad, **extra})
        self._name_as(sd._apply("transpose", [y],
                                attrs={"perm": (0, 3, 1, 2)}, name=out), out)
        self.rank[out] = 4

    def _map_batchnorm(self, ins, out, a):
        """BN over NCHW: reshape (C,) stats to broadcast over axis 1."""
        x_rank = self.rank.get(ins[0], 4)
        sd = self.sd
        eps = a.get("epsilon", 1e-5)

        def shaped(name):
            arr = self._const_of(name)
            if x_rank > 2:
                arr = arr.reshape(arr.shape[0], *([1] * (x_rank - 2)))
            return sd.constant(arr)

        scale, bias, mean, var = (shaped(ins[1]), shaped(ins[2]),
                                  shaped(ins[3]), shaped(ins[4]))
        self._name_as(sd._apply(
            "batch_norm", [self._ensure_var(ins[0]), mean, var, scale, bias],
            attrs={"eps": eps}, name=out), out)
        self.rank[out] = x_rank

    def _map_slice(self, ins, out, a):
        starts = (tuple(int(s) for s in self._const_of(ins[1]))
                  if len(ins) > 1 else tuple(a.get("starts", ())))
        ends = (tuple(int(s) for s in self._const_of(ins[2]))
                if len(ins) > 2 else tuple(a.get("ends", ())))
        axes = (tuple(int(s) for s in self._const_of(ins[3]))
                if len(ins) > 3 else tuple(a.get("axes", range(len(starts)))))
        steps = (tuple(int(s) for s in self._const_of(ins[4]))
                 if len(ins) > 4 else (1,) * len(starts))
        # expand the (starts, ends, axes, steps) form to full rank
        r = self.rank.get(ins[0], max(axes) + 1 if axes else len(starts))
        begin, end, strides = [0] * r, [2**31 - 1] * r, [1] * r
        for i, ax in enumerate(axes):
            begin[ax], end[ax], strides[ax] = starts[i], ends[i], steps[i]
        self._emit("strided_slice", [ins[0]], out, begin=tuple(begin),
                   end=tuple(end), strides=tuple(strides))
        self.rank[out] = r
