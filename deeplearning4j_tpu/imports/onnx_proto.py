"""Minimal ONNX protobuf reader — no ``onnx`` package in this environment.

Implements the protobuf wire format (varint / fixed32 / fixed64 /
length-delimited) plus schema tables for the ONNX message subset an importer
needs (ModelProto, GraphProto, NodeProto, AttributeProto, TensorProto,
ValueInfoProto). Field numbers follow the public, frozen ``onnx.proto3``
schema. Parsed messages are plain dicts; tensors decode to numpy arrays.

The reference reads ONNX through protobuf-generated Java classes
(``org.nd4j.imports.graphmapper.onnx.OnnxGraphMapper``); here the schema is
small enough that a table-driven decoder is simpler than shipping generated
code.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Tuple

import numpy as np

# ---------------------------------------------------------------- wire format

_WIRE_VARINT, _WIRE_FIXED64, _WIRE_LEN, _WIRE_FIXED32 = 0, 1, 2, 5


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _skip(buf: bytes, pos: int, wire: int) -> int:
    if wire == _WIRE_VARINT:
        return _read_varint(buf, pos)[1]
    if wire == _WIRE_FIXED64:
        return pos + 8
    if wire == _WIRE_LEN:
        n, pos = _read_varint(buf, pos)
        return pos + n
    if wire == _WIRE_FIXED32:
        return pos + 4
    raise ValueError(f"unsupported wire type {wire}")


# ------------------------------------------------------------------- schemas
# field_no -> (name, kind); kind: 'int' | 'float32' | 'double' | 'str' |
# 'bytes' | 'msg:<Schema>' ; repeated fields get list-append semantics
# (packed scalar arrays are handled for 'int'/'float32'/'double').

_SCHEMAS: Dict[str, Dict[int, Tuple[str, str, bool]]] = {
    "ModelProto": {
        1: ("ir_version", "int", False),
        2: ("producer_name", "str", False),
        7: ("graph", "msg:GraphProto", False),
        8: ("opset_import", "msg:OperatorSetIdProto", True),
    },
    "OperatorSetIdProto": {
        1: ("domain", "str", False),
        2: ("version", "int", False),
    },
    "GraphProto": {
        1: ("node", "msg:NodeProto", True),
        2: ("name", "str", False),
        5: ("initializer", "msg:TensorProto", True),
        11: ("input", "msg:ValueInfoProto", True),
        12: ("output", "msg:ValueInfoProto", True),
        13: ("value_info", "msg:ValueInfoProto", True),
    },
    "NodeProto": {
        1: ("input", "str", True),
        2: ("output", "str", True),
        3: ("name", "str", False),
        4: ("op_type", "str", False),
        5: ("attribute", "msg:AttributeProto", True),
        7: ("domain", "str", False),
    },
    "AttributeProto": {
        1: ("name", "str", False),
        2: ("f", "float32", False),
        3: ("i", "int", False),
        4: ("s", "bytes", False),
        5: ("t", "msg:TensorProto", False),
        6: ("g", "msg:GraphProto", False),
        7: ("floats", "float32", True),
        8: ("ints", "int", True),
        9: ("strings", "bytes", True),
        10: ("tensors", "msg:TensorProto", True),
        20: ("type", "int", False),
    },
    "TensorProto": {
        1: ("dims", "int", True),
        2: ("data_type", "int", False),
        4: ("float_data", "float32", True),
        5: ("int32_data", "int", True),
        6: ("string_data", "bytes", True),
        7: ("int64_data", "int", True),
        8: ("name", "str", False),
        9: ("raw_data", "bytes", False),
        10: ("double_data", "double", True),
        11: ("uint64_data", "int", True),
    },
    "ValueInfoProto": {
        1: ("name", "str", False),
        2: ("type", "msg:TypeProto", False),
    },
    "TypeProto": {
        1: ("tensor_type", "msg:TypeProto.Tensor", False),
    },
    "TypeProto.Tensor": {
        1: ("elem_type", "int", False),
        2: ("shape", "msg:TensorShapeProto", False),
    },
    "TensorShapeProto": {
        1: ("dim", "msg:TensorShapeProto.Dimension", True),
    },
    "TensorShapeProto.Dimension": {
        1: ("dim_value", "int", False),
        2: ("dim_param", "str", False),
    },
}


def parse(buf: bytes, schema_name: str) -> Dict[str, Any]:
    """Decode one message of ``schema_name`` into a dict (repeated -> list)."""
    schema = _SCHEMAS[schema_name]
    out: Dict[str, Any] = {}
    pos, end = 0, len(buf)
    while pos < end:
        tag, pos = _read_varint(buf, pos)
        field_no, wire = tag >> 3, tag & 7
        spec = schema.get(field_no)
        if spec is None:
            pos = _skip(buf, pos, wire)
            continue
        name, kind, repeated = spec
        if kind.startswith("msg:"):
            n, pos = _read_varint(buf, pos)
            val = parse(buf[pos:pos + n], kind[4:])
            pos += n
        elif wire == _WIRE_LEN and kind in ("int", "float32", "double"):
            # packed repeated scalars
            n, pos = _read_varint(buf, pos)
            chunk, pos = buf[pos:pos + n], pos + n
            if kind == "int":
                vals, p = [], 0
                while p < len(chunk):
                    v, p = _read_varint(chunk, p)
                    vals.append(_to_signed(v))
                out.setdefault(name, []).extend(vals)
                continue
            fmt, width = ("<f", 4) if kind == "float32" else ("<d", 8)
            vals = [struct.unpack_from(fmt, chunk, i)[0]
                    for i in range(0, len(chunk), width)]
            out.setdefault(name, []).extend(vals)
            continue
        elif kind == "int":
            v, pos = _read_varint(buf, pos)
            val = _to_signed(v)
        elif kind == "float32":
            val = struct.unpack_from("<f", buf, pos)[0]
            pos += 4
        elif kind == "double":
            val = struct.unpack_from("<d", buf, pos)[0]
            pos += 8
        elif kind in ("str", "bytes"):
            n, pos = _read_varint(buf, pos)
            raw = buf[pos:pos + n]
            pos += n
            val = raw.decode("utf-8", "replace") if kind == "str" else raw
        else:
            raise ValueError(f"bad kind {kind}")
        if repeated:
            out.setdefault(name, []).append(val)
        else:
            out[name] = val
    return out


def _to_signed(v: int) -> int:
    """int64 fields arrive as two's-complement varints."""
    return v - (1 << 64) if v >= (1 << 63) else v


# ---------------------------------------------------------------- tensor load

_DTYPES = {
    1: np.float32, 2: np.uint8, 3: np.int8, 4: np.uint16, 5: np.int16,
    6: np.int32, 7: np.int64, 9: np.bool_, 10: np.float16, 11: np.float64,
    12: np.uint32, 13: np.uint64,
}


def tensor_to_numpy(t: Dict[str, Any]) -> np.ndarray:
    dims = tuple(t.get("dims", []))
    dt = _DTYPES.get(t.get("data_type", 1))
    if dt is None:
        raise ValueError(f"unsupported ONNX tensor dtype {t.get('data_type')}")
    raw = t.get("raw_data")
    if raw is not None:
        return np.frombuffer(raw, dtype=dt).reshape(dims).copy()
    for field, cast in (("float_data", np.float32), ("int64_data", np.int64),
                        ("int32_data", np.int32), ("double_data", np.float64),
                        ("uint64_data", np.uint64)):
        if field in t:
            return np.asarray(t[field], dtype=cast).astype(dt).reshape(dims)
    return np.zeros(dims, dtype=dt)


def load_model(path_or_bytes) -> Dict[str, Any]:
    if isinstance(path_or_bytes, bytes):
        data = path_or_bytes
    else:
        with open(path_or_bytes, "rb") as f:
            data = f.read()
    return parse(data, "ModelProto")
