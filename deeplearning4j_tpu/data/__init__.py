"""Data layer: DataSet, iterators, normalizers, dataset fetchers, ETL.

Rebuild of the reference's data stack: ``org.nd4j.linalg.dataset``
(``DataSet``/``MultiDataSet``), the ``DataSetIterator`` SPI + async prefetch
(``AsyncDataSetIterator``), normalizers
(``org.nd4j.linalg.dataset.api.preprocessor``), built-in dataset
iterators (``org.deeplearning4j.datasets``), and a DataVec-style declarative
ETL pipeline (``records`` module: RecordReader / Schema / TransformProcess).
"""

from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.data.iterators import (
    AsyncDataSetIterator,
    DataSetIterator,
    ExistingDataSetIterator,
    ListDataSetIterator,
    NumpyDataSetIterator,
)
from deeplearning4j_tpu.data.normalizers import (
    ImagePreProcessingScaler,
    NormalizerMinMaxScaler,
    NormalizerStandardize,
)
from deeplearning4j_tpu.data.mnist import MnistDataSetIterator
from deeplearning4j_tpu.data.fetchers import (
    Cifar10DataSetIterator,
    EmnistDataSetIterator,
    IrisDataSetIterator,
    SvhnDataSetIterator,
    TinyImageNetDataSetIterator,
    UciSequenceDataSetIterator,
)
from deeplearning4j_tpu.data.image import (
    CropImageTransform,
    FlipImageTransform,
    ImageRecordReader,
    ImageRecordReaderDataSetIterator,
    ImageTransform,
    PipelineImageTransform,
    RandomCropTransform,
    ResizeImageTransform,
    RotateImageTransform,
    ScaleImageTransform,
    WarpImageTransform,
)

__all__ = [
    "DataSet", "MultiDataSet", "DataSetIterator", "ListDataSetIterator",
    "NumpyDataSetIterator", "ExistingDataSetIterator", "AsyncDataSetIterator",
    "NormalizerStandardize", "NormalizerMinMaxScaler", "ImagePreProcessingScaler",
    "MnistDataSetIterator", "IrisDataSetIterator", "Cifar10DataSetIterator",
    "SvhnDataSetIterator", "EmnistDataSetIterator", "TinyImageNetDataSetIterator",
    "UciSequenceDataSetIterator", "ImageRecordReader",
    "ImageRecordReaderDataSetIterator", "ImageTransform", "CropImageTransform",
    "RandomCropTransform", "FlipImageTransform", "RotateImageTransform",
    "ScaleImageTransform", "ResizeImageTransform", "WarpImageTransform",
    "PipelineImageTransform",
]
