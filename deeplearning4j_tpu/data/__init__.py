"""Data layer: DataSet, iterators, normalizers, dataset fetchers, ETL.

Rebuild of the reference's data stack: ``org.nd4j.linalg.dataset``
(``DataSet``/``MultiDataSet``), the ``DataSetIterator`` SPI + async prefetch
(``AsyncDataSetIterator``), normalizers
(``org.nd4j.linalg.dataset.api.preprocessor``), built-in dataset
iterators (``org.deeplearning4j.datasets``), and a DataVec-style declarative
ETL pipeline (``records`` module: RecordReader / Schema / TransformProcess).
"""

from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.data.iterators import (
    AsyncDataSetIterator,
    DataSetIterator,
    ExistingDataSetIterator,
    ListDataSetIterator,
    NumpyDataSetIterator,
)
from deeplearning4j_tpu.data.normalizers import (
    ImagePreProcessingScaler,
    NormalizerMinMaxScaler,
    NormalizerStandardize,
)
from deeplearning4j_tpu.data.mnist import MnistDataSetIterator

__all__ = [
    "DataSet", "MultiDataSet", "DataSetIterator", "ListDataSetIterator",
    "NumpyDataSetIterator", "ExistingDataSetIterator", "AsyncDataSetIterator",
    "NormalizerStandardize", "NormalizerMinMaxScaler", "ImagePreProcessingScaler",
    "MnistDataSetIterator",
]
