"""MNIST / EMNIST-style dataset iterators.

Reference: ``org.deeplearning4j.datasets.iterator.impl.MnistDataSetIterator``
+ ``MnistDataFetcher``. The reference downloads and caches under
``~/.deeplearning4j``; this environment is offline, so resolution order is:

1. IDX files in ``DL4J_TPU_DATA_DIR`` (or ``~/.deeplearning4j_tpu/mnist``)
   — standard ``train-images-idx3-ubyte`` naming, the same files the
   reference caches, so an existing cache can be pointed at directly;
2. otherwise a deterministic synthetic MNIST substitute (class-conditional
   digit-like blobs) so the full pipeline trains offline. Clearly flagged via
   ``.synthetic``.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.data.iterators import NumpyDataSetIterator

_DEFAULT_DIRS = (
    os.environ.get("DL4J_TPU_DATA_DIR", ""),
    os.path.expanduser("~/.deeplearning4j_tpu/mnist"),
    os.path.expanduser("~/.deeplearning4j/mnist"),
)


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), np.uint8).reshape(dims)


def _find_idx_files(train: bool) -> Optional[Tuple[str, str]]:
    img = "train-images-idx3-ubyte" if train else "t10k-images-idx3-ubyte"
    lab = "train-labels-idx1-ubyte" if train else "t10k-labels-idx1-ubyte"
    for d in _DEFAULT_DIRS:
        if not d:
            continue
        for suffix in ("", ".gz"):
            ip, lp = os.path.join(d, img + suffix), os.path.join(d, lab + suffix)
            if os.path.exists(ip) and os.path.exists(lp):
                return ip, lp
    return None


def _synthetic_mnist(n: int, seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic class-separable 28x28 images: each class is a Gaussian
    blob pattern + noise. Linearly separable enough that LeNet reaches high
    accuracy — useful as an offline smoke/benchmark dataset."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=n)
    yy, xx = np.mgrid[0:28, 0:28].astype(np.float32)
    images = np.empty((n, 28, 28), np.float32)
    for c in range(10):
        cx, cy = 6 + (c % 5) * 4, 8 + (c // 5) * 10
        base = np.exp(-(((xx - cx) ** 2 + (yy - cy) ** 2) / (2 * 9.0)))
        idx = labels == c
        k = int(idx.sum())
        images[idx] = base[None] * 200.0 + rng.normal(0, 20, (k, 28, 28))
    return np.clip(images, 0, 255).astype(np.float32), labels.astype(np.int64)


class MnistDataSetIterator(NumpyDataSetIterator):
    def __init__(self, batch_size: int, train: bool = True, seed: int = 6,
                 num_examples: Optional[int] = None, flatten: bool = True,
                 shuffle: Optional[bool] = None):
        files = _find_idx_files(train)
        if files is not None:
            images = _read_idx(files[0]).astype(np.float32)
            labels = _read_idx(files[1]).astype(np.int64)
            self.synthetic = False
        else:
            n = num_examples or (60000 if train else 10000)
            images, labels = _synthetic_mnist(n, seed + (0 if train else 1))
            self.synthetic = True
        if num_examples is not None:
            images, labels = images[:num_examples], labels[:num_examples]
        images = images / 255.0
        features = images.reshape(len(images), -1) if flatten else images[..., None]
        onehot = np.zeros((len(labels), 10), np.float32)
        onehot[np.arange(len(labels)), labels] = 1.0
        super().__init__(features, onehot, batch_size,
                         shuffle=train if shuffle is None else shuffle, seed=seed)
