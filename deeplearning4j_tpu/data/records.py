"""DataVec-style declarative ETL: record readers, schema, transform process.

Rebuild of the reference's datavec-api (upstream
``org.datavec.api.records.reader.*``, ``org.datavec.api.transform.*``):
``RecordReader`` SPI (CSV/line/collection/sequence), typed ``Schema``,
declarative ``TransformProcess`` (column ops, filters, conditional
replacement, math ops, categorical encodings), a local executor, and the
``RecordReaderDataSetIterator`` bridge into training.

Records are python lists of primitive values (the Writable type system
collapses to python scalars — same information, no boxing); heavy numeric
batching happens in numpy at the iterator bridge, which is where the TPU
feed path begins.
"""

from __future__ import annotations

import csv
import dataclasses
import enum
import io
import math
import os
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Union

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import DataSetIterator


# --------------------------------------------------------------- record readers
class RecordReader:
    """SPI (reference ``RecordReader``): iterate records = lists of values."""

    def __iter__(self):
        self.reset()
        return self

    def __next__(self) -> List[Any]:
        if not self.has_next():
            raise StopIteration
        return self.next()

    def has_next(self) -> bool:
        raise NotImplementedError

    def next(self) -> List[Any]:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError


class CollectionRecordReader(RecordReader):
    def __init__(self, records: Sequence[List[Any]]):
        self.records = list(records)
        self._pos = 0

    def has_next(self):
        return self._pos < len(self.records)

    def next(self):
        r = self.records[self._pos]
        self._pos += 1
        return list(r)

    def reset(self):
        self._pos = 0


class CSVRecordReader(RecordReader):
    """Reference ``CSVRecordReader``: delimiter/quote handling, skip lines,
    numeric auto-parse."""

    def __init__(self, skip_num_lines: int = 0, delimiter: str = ",",
                 quote: str = '"', parse_numbers: bool = True):
        self.skip = skip_num_lines
        self.delimiter = delimiter
        self.quote = quote
        self.parse_numbers = parse_numbers
        self._rows: List[List[Any]] = []
        self._pos = 0

    def initialize(self, source: Union[str, io.TextIOBase, Sequence[str]]) -> "CSVRecordReader":
        if isinstance(source, str):
            with open(source, newline="") as f:
                rows = list(csv.reader(f, delimiter=self.delimiter, quotechar=self.quote))
        elif isinstance(source, io.TextIOBase):
            rows = list(csv.reader(source, delimiter=self.delimiter, quotechar=self.quote))
        else:
            rows = list(csv.reader(list(source), delimiter=self.delimiter,
                                   quotechar=self.quote))
        rows = rows[self.skip:]
        if self.parse_numbers:
            rows = [[_maybe_num(v) for v in r] for r in rows]
        self._rows = rows
        self._pos = 0
        return self

    def has_next(self):
        return self._pos < len(self._rows)

    def next(self):
        r = self._rows[self._pos]
        self._pos += 1
        return list(r)

    def reset(self):
        self._pos = 0


class LineRecordReader(RecordReader):
    def __init__(self):
        self._lines: List[str] = []
        self._pos = 0

    def initialize(self, source: Union[str, Sequence[str]]) -> "LineRecordReader":
        if isinstance(source, str):
            with open(source) as f:
                self._lines = [l.rstrip("\n") for l in f]
        else:
            self._lines = list(source)
        self._pos = 0
        return self

    def has_next(self):
        return self._pos < len(self._lines)

    def next(self):
        l = self._lines[self._pos]
        self._pos += 1
        return [l]

    def reset(self):
        self._pos = 0


class RegexLineRecordReader(RecordReader):
    """Parse each line with a regex; groups become the record's columns
    (reference ``RegexLineRecordReader``)."""

    def __init__(self, regex: str, skip_num_lines: int = 0):
        import re
        self._re = re.compile(regex)
        self.skip = skip_num_lines
        self._records: List[List[Any]] = []
        self._pos = 0

    def initialize(self, source: Union[str, Sequence[str]]) -> "RegexLineRecordReader":
        lines = (open(source).read().splitlines()
                 if isinstance(source, str) else list(source))
        self._records = []
        for line in lines[self.skip:]:
            m = self._re.match(line)
            if m is None:
                raise ValueError(f"Line does not match regex: {line!r}")
            self._records.append([_maybe_num(g) for g in m.groups()])
        self._pos = 0
        return self

    def has_next(self):
        return self._pos < len(self._records)

    def next(self):
        r = self._records[self._pos]
        self._pos += 1
        return r

    def reset(self):
        self._pos = 0


class JacksonLineRecordReader(RecordReader):
    """JSON-object-per-line reader (reference ``JacksonLineRecordReader``):
    ``field_selection`` lists the keys to extract, in column order."""

    def __init__(self, field_selection: Sequence[str]):
        self.fields = list(field_selection)
        self._records: List[List[Any]] = []
        self._pos = 0

    def initialize(self, source: Union[str, Sequence[str]]) -> "JacksonLineRecordReader":
        import json as _json
        lines = (open(source).read().splitlines()
                 if isinstance(source, str) else list(source))
        self._records = []
        for line in lines:
            if not line.strip():
                continue
            obj = _json.loads(line)
            self._records.append([obj.get(f) for f in self.fields])
        self._pos = 0
        return self

    def has_next(self):
        return self._pos < len(self._records)

    def next(self):
        r = self._records[self._pos]
        self._pos += 1
        return r

    def reset(self):
        self._pos = 0


class CSVSequenceRecordReader(RecordReader):
    """One CSV file per sequence (reference ``CSVSequenceRecordReader``).
    ``next()`` returns a list of timestep records."""

    def __init__(self, skip_num_lines: int = 0, delimiter: str = ","):
        self.skip = skip_num_lines
        self.delimiter = delimiter
        self._seqs: List[List[List[Any]]] = []
        self._pos = 0

    def initialize(self, paths: Sequence[str]) -> "CSVSequenceRecordReader":
        self._seqs = []
        for p in paths:
            rr = CSVRecordReader(self.skip, self.delimiter).initialize(p)
            self._seqs.append([r for r in rr])
        self._pos = 0
        return self

    def has_next(self):
        return self._pos < len(self._seqs)

    def next(self):
        s = self._seqs[self._pos]
        self._pos += 1
        return s

    def reset(self):
        self._pos = 0


def _maybe_num(v: str):
    try:
        f = float(v)
        return int(f) if f.is_integer() and "." not in v and "e" not in v.lower() else f
    except (ValueError, TypeError):
        return v


# ----------------------------------------------------------------------- schema
class ColumnType(str, enum.Enum):
    STRING = "string"
    INTEGER = "integer"
    DOUBLE = "double"
    CATEGORICAL = "categorical"
    LONG = "long"
    TIME = "time"


@dataclasses.dataclass
class ColumnMeta:
    name: str
    type: ColumnType
    categories: Optional[List[str]] = None


class Schema:
    """Typed column schema (reference ``org.datavec.api.transform.schema.Schema``)."""

    def __init__(self, columns: List[ColumnMeta]):
        self.columns = columns

    @property
    def names(self) -> List[str]:
        return [c.name for c in self.columns]

    def index_of(self, name: str) -> int:
        return self.names.index(name)

    def column(self, name: str) -> ColumnMeta:
        return self.columns[self.index_of(name)]

    class Builder:
        def __init__(self):
            self._cols: List[ColumnMeta] = []

        def add_column_string(self, *names):
            for n in names:
                self._cols.append(ColumnMeta(n, ColumnType.STRING))
            return self

        def add_column_integer(self, *names):
            for n in names:
                self._cols.append(ColumnMeta(n, ColumnType.INTEGER))
            return self

        def add_column_double(self, *names):
            for n in names:
                self._cols.append(ColumnMeta(n, ColumnType.DOUBLE))
            return self

        def add_column_categorical(self, name, categories):
            self._cols.append(ColumnMeta(name, ColumnType.CATEGORICAL, list(categories)))
            return self

        def build(self) -> "Schema":
            return Schema(list(self._cols))

    @staticmethod
    def builder() -> "Schema.Builder":
        return Schema.Builder()

    def to_dict(self):
        return {"columns": [{"name": c.name, "type": c.type.value,
                             "categories": c.categories} for c in self.columns]}

    @staticmethod
    def from_dict(d):
        return Schema([ColumnMeta(c["name"], ColumnType(c["type"]), c.get("categories"))
                       for c in d["columns"]])


# -------------------------------------------------------------------- transforms
@dataclasses.dataclass
class _Step:
    kind: str
    args: Dict[str, Any]

    def apply_schema(self, schema: Schema) -> Schema:
        return _SCHEMA_FNS[self.kind](schema, **self.args)

    def apply_records(self, schema: Schema, records: List[List[Any]]) -> List[List[Any]]:
        return _RECORD_FNS[self.kind](schema, records, **self.args)


_SCHEMA_FNS: Dict[str, Callable] = {}
_RECORD_FNS: Dict[str, Callable] = {}


def _step(kind):
    def deco_schema(fn):
        _SCHEMA_FNS[kind] = fn
        return fn
    return deco_schema


def _rec(kind):
    def deco(fn):
        _RECORD_FNS[kind] = fn
        return fn
    return deco


# remove columns
@_step("remove_columns")
def _s_remove(schema, names):
    return Schema([c for c in schema.columns if c.name not in names])


@_rec("remove_columns")
def _r_remove(schema, records, names):
    idx = [i for i, c in enumerate(schema.columns) if c.name not in names]
    return [[r[i] for i in idx] for r in records]


# keep only
@_step("remove_all_columns_except")
def _s_keep(schema, names):
    return Schema([c for c in schema.columns if c.name in names])


@_rec("remove_all_columns_except")
def _r_keep(schema, records, names):
    idx = [i for i, c in enumerate(schema.columns) if c.name in names]
    return [[r[i] for i in idx] for r in records]


# rename
@_step("rename_column")
def _s_rename(schema, old, new):
    return Schema([dataclasses.replace(c, name=new) if c.name == old else c
                   for c in schema.columns])


@_rec("rename_column")
def _r_rename(schema, records, old, new):
    return records


# categorical -> integer
@_step("categorical_to_integer")
def _s_cat2int(schema, name):
    return Schema([dataclasses.replace(c, type=ColumnType.INTEGER, categories=None)
                   if c.name == name else c for c in schema.columns])


@_rec("categorical_to_integer")
def _r_cat2int(schema, records, name):
    i = schema.index_of(name)
    cats = schema.columns[i].categories
    lut = {c: j for j, c in enumerate(cats)}
    out = []
    for r in records:
        r = list(r)
        r[i] = lut[r[i]]
        out.append(r)
    return out


# categorical -> one-hot
@_step("categorical_to_one_hot")
def _s_cat2oh(schema, name):
    cols = []
    for c in schema.columns:
        if c.name == name:
            for cat in c.categories:
                cols.append(ColumnMeta(f"{name}[{cat}]", ColumnType.INTEGER))
        else:
            cols.append(c)
    return Schema(cols)


@_rec("categorical_to_one_hot")
def _r_cat2oh(schema, records, name):
    i = schema.index_of(name)
    cats = schema.columns[i].categories
    out = []
    for r in records:
        oh = [1 if r[i] == c else 0 for c in cats]
        out.append(r[:i] + oh + r[i + 1:])
    return out


# filter rows
@_step("filter")
def _s_filter(schema, predicate):
    return schema


@_rec("filter")
def _r_filter(schema, records, predicate):
    names = schema.names
    return [r for r in records if not predicate(dict(zip(names, r)))]


# math op on a double/int column
@_step("double_math_op")
def _s_math(schema, name, op, value):
    return schema


@_rec("double_math_op")
def _r_math(schema, records, name, op, value):
    i = schema.index_of(name)
    fn = {"add": lambda x: x + value, "subtract": lambda x: x - value,
          "multiply": lambda x: x * value, "divide": lambda x: x / value,
          "power": lambda x: x ** value, "min": lambda x: min(x, value),
          "max": lambda x: max(x, value)}[op]
    out = []
    for r in records:
        r = list(r)
        r[i] = fn(r[i])
        out.append(r)
    return out


# conditional replace
@_step("conditional_replace")
def _s_cond(schema, name, predicate, replacement):
    return schema


@_rec("conditional_replace")
def _r_cond(schema, records, name, predicate, replacement):
    i = schema.index_of(name)
    names = schema.names
    out = []
    for r in records:
        r = list(r)
        if predicate(dict(zip(names, r))):
            r[i] = replacement
        out.append(r)
    return out


# normalize (min-max or standardize) — computed over the dataset at execute time
@_step("normalize")
def _s_norm(schema, name, kind):
    return Schema([dataclasses.replace(c, type=ColumnType.DOUBLE)
                   if c.name == name else c for c in schema.columns])


@_rec("normalize")
def _r_norm(schema, records, name, kind):
    i = schema.index_of(name)
    vals = np.asarray([float(r[i]) for r in records])
    if kind == "minmax":
        lo, hi = vals.min(), vals.max()
        scaled = (vals - lo) / max(hi - lo, 1e-12)
    else:
        scaled = (vals - vals.mean()) / max(vals.std(), 1e-12)
    out = []
    for r, v in zip(records, scaled):
        r = list(r)
        r[i] = float(v)
        out.append(r)
    return out


# custom per-record function (escape hatch)
@_step("map_records")
def _s_map(schema, fn, new_schema=None):
    return new_schema or schema


@_rec("map_records")
def _r_map(schema, records, fn, new_schema=None):
    return [fn(list(r)) for r in records]


class TransformProcess:
    """Declarative transform pipeline (reference ``TransformProcess``)."""

    def __init__(self, initial_schema: Schema, steps: List[_Step]):
        self.initial_schema = initial_schema
        self.steps = steps

    def final_schema(self) -> Schema:
        s = self.initial_schema
        for st in self.steps:
            s = st.apply_schema(s)
        return s

    class Builder:
        def __init__(self, schema: Schema):
            self._schema = schema
            self._steps: List[_Step] = []

        def _add(self, kind, /, **args):
            # positional-only: step args may legitimately be NAMED "kind"
            # (normalize's kind=...) without colliding
            self._steps.append(_Step(kind, args))
            return self

        def remove_columns(self, *names):
            return self._add("remove_columns", names=list(names))

        def remove_all_columns_except(self, *names):
            return self._add("remove_all_columns_except", names=list(names))

        def rename_column(self, old, new):
            return self._add("rename_column", old=old, new=new)

        def categorical_to_integer(self, name):
            return self._add("categorical_to_integer", name=name)

        def categorical_to_one_hot(self, name):
            return self._add("categorical_to_one_hot", name=name)

        def filter(self, predicate):
            """Remove rows where predicate(row_dict) is True."""
            return self._add("filter", predicate=predicate)

        def double_math_op(self, name, op, value):
            return self._add("double_math_op", name=name, op=op, value=value)

        def conditional_replace_value_transform(self, name, predicate, replacement):
            return self._add("conditional_replace", name=name, predicate=predicate,
                             replacement=replacement)

        def normalize(self, name, kind="standardize"):
            return self._add("normalize", name=name, kind=kind)

        def map_records(self, fn, new_schema=None):
            return self._add("map_records", fn=fn, new_schema=new_schema)

        def build(self) -> "TransformProcess":
            return TransformProcess(self._schema, list(self._steps))

    @staticmethod
    def builder(schema: Schema) -> "TransformProcess.Builder":
        return TransformProcess.Builder(schema)


# ---------------------------------------------------------------- reductions
class ReduceOp(str, enum.Enum):
    """Reference ``org.datavec.api.transform.ops.ReduceOp``."""

    SUM = "sum"
    MEAN = "mean"
    MIN = "min"
    MAX = "max"
    RANGE = "range"
    COUNT = "count"
    COUNT_UNIQUE = "count_unique"
    STDEV = "stdev"
    FIRST = "first"
    LAST = "last"


_REDUCE_FNS = {
    ReduceOp.SUM: lambda vs: float(np.sum(vs)),
    ReduceOp.MEAN: lambda vs: float(np.mean(vs)),
    ReduceOp.MIN: lambda vs: min(vs),
    ReduceOp.MAX: lambda vs: max(vs),
    ReduceOp.RANGE: lambda vs: float(max(vs)) - float(min(vs)),
    ReduceOp.COUNT: lambda vs: len(vs),
    ReduceOp.COUNT_UNIQUE: lambda vs: len(set(vs)),
    ReduceOp.STDEV: lambda vs: float(np.std(np.asarray(vs, np.float64), ddof=1))
    if len(vs) > 1 else 0.0,
    ReduceOp.FIRST: lambda vs: vs[0],
    ReduceOp.LAST: lambda vs: vs[-1],
}

# ops whose output keeps the input column type (others become DOUBLE/INTEGER)
_TYPE_PRESERVING = {ReduceOp.MIN, ReduceOp.MAX, ReduceOp.FIRST, ReduceOp.LAST}


class Reducer:
    """Group-by + per-column aggregation (reference
    ``org.datavec.api.transform.reduce.Reducer``)::

        r = (Reducer.builder("user")
             .sum_columns("amount").count_columns("txn").build())
    """

    def __init__(self, key_columns: List[str], ops: List[tuple]):
        self.key_columns = list(key_columns)
        self.ops = ops  # [(column, ReduceOp)]

    class Builder:
        def __init__(self, *key_columns: str):
            self._keys = list(key_columns)
            self._ops: List[tuple] = []

        def _add(self, op, names):
            self._ops.extend((n, op) for n in names)
            return self

        def sum_columns(self, *names):
            return self._add(ReduceOp.SUM, names)

        def mean_columns(self, *names):
            return self._add(ReduceOp.MEAN, names)

        def min_columns(self, *names):
            return self._add(ReduceOp.MIN, names)

        def max_columns(self, *names):
            return self._add(ReduceOp.MAX, names)

        def range_columns(self, *names):
            return self._add(ReduceOp.RANGE, names)

        def count_columns(self, *names):
            return self._add(ReduceOp.COUNT, names)

        def count_unique_columns(self, *names):
            return self._add(ReduceOp.COUNT_UNIQUE, names)

        def stdev_columns(self, *names):
            return self._add(ReduceOp.STDEV, names)

        def first_columns(self, *names):
            return self._add(ReduceOp.FIRST, names)

        def last_columns(self, *names):
            return self._add(ReduceOp.LAST, names)

        def build(self) -> "Reducer":
            return Reducer(self._keys, list(self._ops))

    @staticmethod
    def builder(*key_columns: str) -> "Reducer.Builder":
        return Reducer.Builder(*key_columns)

    def output_schema(self, schema: Schema) -> Schema:
        cols = [dataclasses.replace(schema.column(k)) for k in self.key_columns]
        for name, op in self.ops:
            src = schema.column(name)
            if op in _TYPE_PRESERVING:
                typ, cats = src.type, src.categories
            elif op in (ReduceOp.COUNT, ReduceOp.COUNT_UNIQUE):
                typ, cats = ColumnType.INTEGER, None
            else:
                typ, cats = ColumnType.DOUBLE, None
            cols.append(ColumnMeta(f"{op.value}({name})", typ, cats))
        return Schema(cols)

    def reduce(self, schema: Schema, records: List[List[Any]]) -> List[List[Any]]:
        key_idx = [schema.index_of(k) for k in self.key_columns]
        op_idx = [(schema.index_of(n), op) for n, op in self.ops]
        groups: Dict[tuple, List[List[Any]]] = {}
        order: List[tuple] = []
        for r in records:
            k = tuple(r[i] for i in key_idx)
            if k not in groups:
                groups[k] = []
                order.append(k)
            groups[k].append(r)
        out = []
        for k in order:
            rows = groups[k]
            rec = list(k)
            for ci, op in op_idx:
                rec.append(_REDUCE_FNS[op]([r[ci] for r in rows]))
            out.append(rec)
        return out


@_step("reduce")
def _s_reduce(schema, reducer):
    return reducer.output_schema(schema)


@_rec("reduce")
def _r_reduce(schema, records, reducer):
    return reducer.reduce(schema, records)


TransformProcess.Builder.reduce = lambda self, reducer: self._add(
    "reduce", reducer=reducer)


# --------------------------------------------------------------------- joins
class Join:
    """Join two record sets on key columns (reference
    ``org.datavec.api.transform.join.Join``): Inner / LeftOuter / RightOuter /
    FullOuter. Right-side key columns are not duplicated in the output."""

    TYPES = ("Inner", "LeftOuter", "RightOuter", "FullOuter")

    def __init__(self, join_type: str, left_schema: Schema, right_schema: Schema,
                 join_columns: List[str]):
        if join_type not in self.TYPES:
            raise ValueError(f"join_type must be one of {self.TYPES}")
        self.join_type = join_type
        self.left_schema = left_schema
        self.right_schema = right_schema
        self.join_columns = list(join_columns)

    class Builder:
        def __init__(self, join_type: str = "Inner"):
            self._type = join_type
            self._left = self._right = None
            self._cols: List[str] = []

        def set_schemas(self, left: Schema, right: Schema):
            self._left, self._right = left, right
            return self

        def set_join_columns(self, *names: str):
            self._cols = list(names)
            return self

        def build(self) -> "Join":
            return Join(self._type, self._left, self._right, self._cols)

    @staticmethod
    def builder(join_type: str = "Inner") -> "Join.Builder":
        return Join.Builder(join_type)

    def output_schema(self) -> Schema:
        cols = [dataclasses.replace(c) for c in self.left_schema.columns]
        left_names = {c.name for c in cols}
        for c in self.right_schema.columns:
            if c.name in self.join_columns:
                continue
            if c.name in left_names:
                raise ValueError(
                    f"Join would produce duplicate column {c.name!r}; rename "
                    f"it on one side first (name-based addressing would "
                    f"silently resolve to the left column)")
            cols.append(dataclasses.replace(c))
        return Schema(cols)

    def execute(self, left: List[List[Any]], right: List[List[Any]]
                ) -> List[List[Any]]:
        lk = [self.left_schema.index_of(c) for c in self.join_columns]
        rk = [self.right_schema.index_of(c) for c in self.join_columns]
        r_other = [i for i in range(len(self.right_schema.columns)) if i not in rk]
        l_width, r_width = len(self.left_schema.columns), len(r_other)

        rmap: Dict[tuple, List[List[Any]]] = {}
        for r in right:
            rmap.setdefault(tuple(r[i] for i in rk), []).append(r)
        out, matched_right = [], set()
        for l in left:
            key = tuple(l[i] for i in lk)
            matches = rmap.get(key, [])
            if matches:
                matched_right.add(key)
                for r in matches:
                    out.append(list(l) + [r[i] for i in r_other])
            elif self.join_type in ("LeftOuter", "FullOuter"):
                out.append(list(l) + [None] * r_width)
        if self.join_type in ("RightOuter", "FullOuter"):
            key_pos = dict(zip(self.join_columns, lk))
            for key, rows in rmap.items():
                if key in matched_right:
                    continue
                for r in rows:
                    rec: List[Any] = [None] * l_width
                    for c, v in zip(self.join_columns, key):
                        rec[key_pos[c]] = v
                    out.append(rec + [r[i] for i in r_other])
        return out


# ----------------------------------------------------------------- sequences
@_step("convert_to_sequence")
def _s_to_seq(schema, key_column, sort_column):
    return schema


@_rec("convert_to_sequence")
def _r_to_seq(schema, records, key_column, sort_column):
    """Group rows by ``key_column`` into sequences ordered by ``sort_column``
    (reference ``convertToSequence(keyColumn, comparator)``). Output records
    are sequences: lists of rows."""
    ki, si = schema.index_of(key_column), schema.index_of(sort_column)
    groups: Dict[Any, List[List[Any]]] = {}
    order = []
    for r in records:
        k = r[ki]
        if k not in groups:
            groups[k] = []
            order.append(k)
        groups[k].append(r)
    return [sorted(groups[k], key=lambda r: r[si]) for k in order]


@_step("offset_sequence")
def _s_offset_seq(schema, columns, offset):
    return schema


@_rec("offset_sequence")
def _r_offset_seq(schema, records, columns, offset):
    """Shift ``columns`` by ``offset`` steps within each sequence, trimming
    rows without a counterpart (reference ``offsetSequence`` — the standard
    next-step-prediction label construction)."""
    idx = [schema.index_of(c) for c in columns]
    out = []
    for seq in records:
        n = len(seq)
        new_seq = []
        for t in range(n):
            src = t + offset
            if src < 0 or src >= n:
                continue
            row = list(seq[t])
            for i in idx:
                row[i] = seq[src][i]
            new_seq.append(row)
        out.append(new_seq)
    return out


TransformProcess.Builder.convert_to_sequence = lambda self, key_column, sort_column: \
    self._add("convert_to_sequence", key_column=key_column, sort_column=sort_column)
TransformProcess.Builder.offset_sequence = lambda self, columns, offset: \
    self._add("offset_sequence", columns=columns, offset=offset)

_SEQUENCE_STEPS = {"convert_to_sequence", "offset_sequence"}


def _apply_one_step(st: "_Step", schema: Schema, recs, is_seq: bool):
    """Apply one transform step with sequence-mode dispatch; shared by the
    serial and parallel executors so their semantics cannot diverge."""
    if st.kind == "convert_to_sequence":
        recs = st.apply_records(schema, recs)
        is_seq = True
    elif is_seq and st.kind not in _SEQUENCE_STEPS:
        recs = [st.apply_records(schema, seq) for seq in recs]
    else:
        recs = st.apply_records(schema, recs)
    return recs, st.apply_schema(schema), is_seq


class LocalTransformExecutor:
    """Reference ``org.datavec.local.transforms.LocalTransformExecutor``.

    Handles both flat records and (after ``convert_to_sequence``) sequence
    records: flat column steps are applied inside each sequence."""

    @staticmethod
    def execute(records: Iterable[List[Any]], tp: TransformProcess) -> List[List[Any]]:
        recs = [list(r) for r in records]
        schema = tp.initial_schema
        is_seq = False
        for st in tp.steps:
            recs, schema, is_seq = _apply_one_step(st, schema, recs, is_seq)
        return recs

    @staticmethod
    def execute_join(left: Iterable[List[Any]], right: Iterable[List[Any]],
                     join: Join) -> List[List[Any]]:
        return join.execute([list(r) for r in left], [list(r) for r in right])


def _apply_stage(payload):
    """Worker body for ParallelTransformExecutor: run a chain of row-local
    steps over one partition (module-level so it pickles)."""
    steps, schema, part = payload
    for st in steps:
        part = st.apply_records(schema, part)
        schema = st.apply_schema(schema)
    return part


class ParallelTransformExecutor:
    """Multi-process TransformProcess execution — the local-cluster analog
    of the reference's ``SparkTransformExecutor`` (upstream
    ``org.datavec.spark.transform.SparkTransformExecutor``), the same way
    the reference tested its Spark ETL with ``local[N]`` masters.

    Consecutive ROW-LOCAL steps (column edits, math ops, filters) form a
    stage that runs over record partitions in a process pool; steps that
    need the whole dataset (normalize's stats, group-by reduce, sequence
    conversion) run between stages on the merged records — the shuffle
    boundary of the Spark original. Like Spark's serializable-function
    requirement, parallel execution needs picklable step args; a stage
    that fails to pickle (lambda predicates) silently degrades to the
    serial executor, preserving results."""

    ROW_LOCAL = {"remove_columns", "remove_all_columns_except",
                 "rename_column", "categorical_to_integer",
                 "categorical_to_one_hot", "conditional_replace",
                 "double_math_op", "filter", "map_records"}

    @staticmethod
    def execute(records: Iterable[List[Any]], tp: TransformProcess,
                num_workers: Optional[int] = None,
                min_partition: int = 256) -> List[List[Any]]:
        import concurrent.futures as cf
        import os
        import pickle

        recs = [list(r) for r in records]
        schema = tp.initial_schema
        nw = num_workers or min(8, os.cpu_count() or 1)
        i, steps = 0, list(tp.steps)
        is_seq = False
        pool = None  # ONE pool reused across stages (spawn cost is real)
        try:
            while i < len(steps):
                stage = []
                while i < len(steps) and not is_seq \
                        and steps[i].kind in ParallelTransformExecutor.ROW_LOCAL:
                    stage.append(steps[i])
                    i += 1
                if stage:
                    parts_n = max(1, min(nw, len(recs) // max(min_partition, 1)))
                    runnable = parts_n > 1
                    if runnable:
                        try:
                            pickle.dumps((stage, schema))
                        except Exception:
                            runnable = False
                    if runnable:
                        bounds = [len(recs) * j // parts_n
                                  for j in range(parts_n + 1)]
                        payloads = [(stage, schema,
                                     recs[bounds[j]:bounds[j + 1]])
                                    for j in range(parts_n)]
                        try:
                            if pool is None:
                                pool = cf.ProcessPoolExecutor(max_workers=nw)
                            out = list(pool.map(_apply_stage, payloads))
                            recs = [r for part in out for r in part]
                        except Exception:
                            # the serial-fallback CONTRACT covers worker-side
                            # pickling/import failures too, not just the
                            # stage-args probe above
                            recs = _apply_stage((stage, schema, recs))
                    else:
                        recs = _apply_stage((stage, schema, recs))
                    for st in stage:
                        schema = st.apply_schema(schema)
                    continue
                st = steps[i]
                i += 1
                recs, schema, is_seq = _apply_one_step(st, schema, recs, is_seq)
        finally:
            if pool is not None:
                pool.shutdown()
        return recs

    execute_join = LocalTransformExecutor.execute_join


# -------------------------------------------------- iterator bridge to training
class RecordReaderDataSetIterator(DataSetIterator):
    """Bridge records -> DataSet minibatches (reference
    ``RecordReaderDataSetIterator``): label column index + number of classes
    (classification, one-hot) or regression mode."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: int = -1, num_classes: Optional[int] = None,
                 regression: bool = False,
                 transform_process: Optional[TransformProcess] = None):
        records = [r for r in reader]
        if transform_process is not None:
            records = LocalTransformExecutor.execute(records, transform_process)
        self._features = []
        self._labels = []
        for r in records:
            li = label_index if label_index >= 0 else len(r) + label_index
            feats = [float(v) for i, v in enumerate(r) if i != li]
            self._features.append(feats)
            if regression:
                self._labels.append([float(r[li])])
            else:
                oh = [0.0] * num_classes
                oh[int(r[li])] = 1.0
                self._labels.append(oh)
        self._x = np.asarray(self._features, np.float32)
        self._y = np.asarray(self._labels, np.float32)
        self._batch = int(batch_size)
        self._pos = 0

    def has_next(self):
        return self._pos < len(self._x)

    def next(self) -> DataSet:
        sl = slice(self._pos, self._pos + self._batch)
        self._pos += self._batch
        return DataSet(self._x[sl], self._y[sl])

    def reset(self):
        self._pos = 0

    def batch(self):
        return self._batch

    def total_examples(self):
        return len(self._x)


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Sequence records -> padded (batch, time, features) DataSets with
    masks (reference ``SequenceRecordReaderDataSetIterator``).
    ``align="start"`` (default, reference ALIGN_START) pads at the end;
    ``align="end"`` (reference ALIGN_END — last-timestep readout) pads at
    the start. Per-timestep label column -> one-hot labels (B, T, C) with
    the labels mask mirroring the features mask."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: int = -1, num_classes: Optional[int] = None,
                 regression: bool = False, align: str = "start"):
        seqs = [s for s in reader]
        feats, labels = [], []
        for s in seqs:
            fs, ls = [], []
            for r in s:
                li = label_index if label_index >= 0 else len(r) + label_index
                fs.append([float(v) for i, v in enumerate(r) if i != li])
                if regression:
                    ls.append([float(r[li])])
                else:
                    oh = [0.0] * num_classes
                    oh[int(r[li])] = 1.0
                    ls.append(oh)
            feats.append(fs)
            labels.append(ls)
        T = max(len(f) for f in feats)
        nf, nl = len(feats[0][0]), len(labels[0][0])
        self._x = np.zeros((len(feats), T, nf), np.float32)
        self._y = np.zeros((len(feats), T, nl), np.float32)
        self._mask = np.zeros((len(feats), T), np.float32)
        for i, (f, l) in enumerate(zip(feats, labels)):
            if align == "end":
                self._x[i, T - len(f):] = f
                self._y[i, T - len(l):] = l
                self._mask[i, T - len(f):] = 1.0
            else:
                self._x[i, :len(f)] = f
                self._y[i, :len(l)] = l
                self._mask[i, :len(f)] = 1.0
        self._batch = int(batch_size)
        self._pos = 0

    def has_next(self):
        return self._pos < len(self._x)

    def next(self) -> DataSet:
        sl = slice(self._pos, self._pos + self._batch)
        self._pos += self._batch
        return DataSet(self._x[sl], self._y[sl],
                       features_mask=self._mask[sl],
                       labels_mask=self._mask[sl])

    def reset(self):
        self._pos = 0

    def batch(self):
        return self._batch


# ------------------------------------------------------------------ analysis
@dataclasses.dataclass
class NumericalColumnAnalysis:
    """Reference ``org.datavec.api.transform.analysis.columns.*Analysis``."""

    count: int = 0
    count_missing: int = 0
    min: float = float("inf")
    max: float = float("-inf")
    mean: float = 0.0
    stdev: float = 0.0


@dataclasses.dataclass
class CategoricalColumnAnalysis:
    count: int = 0
    count_missing: int = 0
    category_counts: Dict[str, int] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class StringColumnAnalysis:
    count: int = 0
    count_missing: int = 0
    min_length: int = 0
    max_length: int = 0
    count_unique: int = 0


class DataAnalysis:
    """Per-column statistics (reference
    ``org.datavec.api.transform.analysis.DataAnalysis``)."""

    def __init__(self, schema: Schema, analyses: Dict[str, Any]):
        self.schema = schema
        self._analyses = analyses

    def column_analysis(self, name: str):
        return self._analyses[name]

    def __str__(self):
        lines = []
        for c in self.schema.columns:
            lines.append(f"{c.name} ({c.type.value}): {self._analyses[c.name]}")
        return "\n".join(lines)


class AnalyzeLocal:
    """Reference ``org.datavec.local.transforms.AnalyzeLocal.analyze``."""

    @staticmethod
    def analyze(schema: Schema, records: Iterable[List[Any]]) -> DataAnalysis:
        recs = [list(r) for r in records]
        analyses: Dict[str, Any] = {}
        for idx, col in enumerate(schema.columns):
            values = [r[idx] for r in recs]
            missing = sum(1 for v in values if v is None or v == "")
            present = [v for v in values if v is not None and v != ""]
            if col.type in (ColumnType.INTEGER, ColumnType.DOUBLE,
                            ColumnType.LONG, ColumnType.TIME):
                nums = np.asarray([float(v) for v in present], np.float64)
                analyses[col.name] = NumericalColumnAnalysis(
                    count=len(present), count_missing=missing,
                    min=float(nums.min()) if len(nums) else float("nan"),
                    max=float(nums.max()) if len(nums) else float("nan"),
                    mean=float(nums.mean()) if len(nums) else float("nan"),
                    stdev=float(nums.std(ddof=1)) if len(nums) > 1 else 0.0)
            elif col.type == ColumnType.CATEGORICAL:
                counts: Dict[str, int] = {}
                for v in present:
                    counts[str(v)] = counts.get(str(v), 0) + 1
                analyses[col.name] = CategoricalColumnAnalysis(
                    count=len(present), count_missing=missing,
                    category_counts=counts)
            else:
                lens = [len(str(v)) for v in present]
                analyses[col.name] = StringColumnAnalysis(
                    count=len(present), count_missing=missing,
                    min_length=min(lens) if lens else 0,
                    max_length=max(lens) if lens else 0,
                    count_unique=len(set(map(str, present))))
        return DataAnalysis(schema, analyses)
