"""DataVec-style declarative ETL: record readers, schema, transform process.

Rebuild of the reference's datavec-api (upstream
``org.datavec.api.records.reader.*``, ``org.datavec.api.transform.*``):
``RecordReader`` SPI (CSV/line/collection/sequence), typed ``Schema``,
declarative ``TransformProcess`` (column ops, filters, conditional
replacement, math ops, categorical encodings), a local executor, and the
``RecordReaderDataSetIterator`` bridge into training.

Records are python lists of primitive values (the Writable type system
collapses to python scalars — same information, no boxing); heavy numeric
batching happens in numpy at the iterator bridge, which is where the TPU
feed path begins.
"""

from __future__ import annotations

import csv
import dataclasses
import enum
import io
import math
import os
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Union

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import DataSetIterator


# --------------------------------------------------------------- record readers
class RecordReader:
    """SPI (reference ``RecordReader``): iterate records = lists of values."""

    def __iter__(self):
        self.reset()
        return self

    def __next__(self) -> List[Any]:
        if not self.has_next():
            raise StopIteration
        return self.next()

    def has_next(self) -> bool:
        raise NotImplementedError

    def next(self) -> List[Any]:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError


class CollectionRecordReader(RecordReader):
    def __init__(self, records: Sequence[List[Any]]):
        self.records = list(records)
        self._pos = 0

    def has_next(self):
        return self._pos < len(self.records)

    def next(self):
        r = self.records[self._pos]
        self._pos += 1
        return list(r)

    def reset(self):
        self._pos = 0


class CSVRecordReader(RecordReader):
    """Reference ``CSVRecordReader``: delimiter/quote handling, skip lines,
    numeric auto-parse."""

    def __init__(self, skip_num_lines: int = 0, delimiter: str = ",",
                 quote: str = '"', parse_numbers: bool = True):
        self.skip = skip_num_lines
        self.delimiter = delimiter
        self.quote = quote
        self.parse_numbers = parse_numbers
        self._rows: List[List[Any]] = []
        self._pos = 0

    def initialize(self, source: Union[str, io.TextIOBase, Sequence[str]]) -> "CSVRecordReader":
        if isinstance(source, str):
            with open(source, newline="") as f:
                rows = list(csv.reader(f, delimiter=self.delimiter, quotechar=self.quote))
        elif isinstance(source, io.TextIOBase):
            rows = list(csv.reader(source, delimiter=self.delimiter, quotechar=self.quote))
        else:
            rows = list(csv.reader(list(source), delimiter=self.delimiter,
                                   quotechar=self.quote))
        rows = rows[self.skip:]
        if self.parse_numbers:
            rows = [[_maybe_num(v) for v in r] for r in rows]
        self._rows = rows
        self._pos = 0
        return self

    def has_next(self):
        return self._pos < len(self._rows)

    def next(self):
        r = self._rows[self._pos]
        self._pos += 1
        return list(r)

    def reset(self):
        self._pos = 0


class LineRecordReader(RecordReader):
    def __init__(self):
        self._lines: List[str] = []
        self._pos = 0

    def initialize(self, source: Union[str, Sequence[str]]) -> "LineRecordReader":
        if isinstance(source, str):
            with open(source) as f:
                self._lines = [l.rstrip("\n") for l in f]
        else:
            self._lines = list(source)
        self._pos = 0
        return self

    def has_next(self):
        return self._pos < len(self._lines)

    def next(self):
        l = self._lines[self._pos]
        self._pos += 1
        return [l]

    def reset(self):
        self._pos = 0


class CSVSequenceRecordReader(RecordReader):
    """One CSV file per sequence (reference ``CSVSequenceRecordReader``).
    ``next()`` returns a list of timestep records."""

    def __init__(self, skip_num_lines: int = 0, delimiter: str = ","):
        self.skip = skip_num_lines
        self.delimiter = delimiter
        self._seqs: List[List[List[Any]]] = []
        self._pos = 0

    def initialize(self, paths: Sequence[str]) -> "CSVSequenceRecordReader":
        self._seqs = []
        for p in paths:
            rr = CSVRecordReader(self.skip, self.delimiter).initialize(p)
            self._seqs.append([r for r in rr])
        self._pos = 0
        return self

    def has_next(self):
        return self._pos < len(self._seqs)

    def next(self):
        s = self._seqs[self._pos]
        self._pos += 1
        return s

    def reset(self):
        self._pos = 0


def _maybe_num(v: str):
    try:
        f = float(v)
        return int(f) if f.is_integer() and "." not in v and "e" not in v.lower() else f
    except (ValueError, TypeError):
        return v


# ----------------------------------------------------------------------- schema
class ColumnType(str, enum.Enum):
    STRING = "string"
    INTEGER = "integer"
    DOUBLE = "double"
    CATEGORICAL = "categorical"
    LONG = "long"
    TIME = "time"


@dataclasses.dataclass
class ColumnMeta:
    name: str
    type: ColumnType
    categories: Optional[List[str]] = None


class Schema:
    """Typed column schema (reference ``org.datavec.api.transform.schema.Schema``)."""

    def __init__(self, columns: List[ColumnMeta]):
        self.columns = columns

    @property
    def names(self) -> List[str]:
        return [c.name for c in self.columns]

    def index_of(self, name: str) -> int:
        return self.names.index(name)

    def column(self, name: str) -> ColumnMeta:
        return self.columns[self.index_of(name)]

    class Builder:
        def __init__(self):
            self._cols: List[ColumnMeta] = []

        def add_column_string(self, *names):
            for n in names:
                self._cols.append(ColumnMeta(n, ColumnType.STRING))
            return self

        def add_column_integer(self, *names):
            for n in names:
                self._cols.append(ColumnMeta(n, ColumnType.INTEGER))
            return self

        def add_column_double(self, *names):
            for n in names:
                self._cols.append(ColumnMeta(n, ColumnType.DOUBLE))
            return self

        def add_column_categorical(self, name, categories):
            self._cols.append(ColumnMeta(name, ColumnType.CATEGORICAL, list(categories)))
            return self

        def build(self) -> "Schema":
            return Schema(list(self._cols))

    @staticmethod
    def builder() -> "Schema.Builder":
        return Schema.Builder()

    def to_dict(self):
        return {"columns": [{"name": c.name, "type": c.type.value,
                             "categories": c.categories} for c in self.columns]}

    @staticmethod
    def from_dict(d):
        return Schema([ColumnMeta(c["name"], ColumnType(c["type"]), c.get("categories"))
                       for c in d["columns"]])


# -------------------------------------------------------------------- transforms
@dataclasses.dataclass
class _Step:
    kind: str
    args: Dict[str, Any]

    def apply_schema(self, schema: Schema) -> Schema:
        return _SCHEMA_FNS[self.kind](schema, **self.args)

    def apply_records(self, schema: Schema, records: List[List[Any]]) -> List[List[Any]]:
        return _RECORD_FNS[self.kind](schema, records, **self.args)


_SCHEMA_FNS: Dict[str, Callable] = {}
_RECORD_FNS: Dict[str, Callable] = {}


def _step(kind):
    def deco_schema(fn):
        _SCHEMA_FNS[kind] = fn
        return fn
    return deco_schema


def _rec(kind):
    def deco(fn):
        _RECORD_FNS[kind] = fn
        return fn
    return deco


# remove columns
@_step("remove_columns")
def _s_remove(schema, names):
    return Schema([c for c in schema.columns if c.name not in names])


@_rec("remove_columns")
def _r_remove(schema, records, names):
    idx = [i for i, c in enumerate(schema.columns) if c.name not in names]
    return [[r[i] for i in idx] for r in records]


# keep only
@_step("remove_all_columns_except")
def _s_keep(schema, names):
    return Schema([c for c in schema.columns if c.name in names])


@_rec("remove_all_columns_except")
def _r_keep(schema, records, names):
    idx = [i for i, c in enumerate(schema.columns) if c.name in names]
    return [[r[i] for i in idx] for r in records]


# rename
@_step("rename_column")
def _s_rename(schema, old, new):
    return Schema([dataclasses.replace(c, name=new) if c.name == old else c
                   for c in schema.columns])


@_rec("rename_column")
def _r_rename(schema, records, old, new):
    return records


# categorical -> integer
@_step("categorical_to_integer")
def _s_cat2int(schema, name):
    return Schema([dataclasses.replace(c, type=ColumnType.INTEGER, categories=None)
                   if c.name == name else c for c in schema.columns])


@_rec("categorical_to_integer")
def _r_cat2int(schema, records, name):
    i = schema.index_of(name)
    cats = schema.columns[i].categories
    lut = {c: j for j, c in enumerate(cats)}
    out = []
    for r in records:
        r = list(r)
        r[i] = lut[r[i]]
        out.append(r)
    return out


# categorical -> one-hot
@_step("categorical_to_one_hot")
def _s_cat2oh(schema, name):
    cols = []
    for c in schema.columns:
        if c.name == name:
            for cat in c.categories:
                cols.append(ColumnMeta(f"{name}[{cat}]", ColumnType.INTEGER))
        else:
            cols.append(c)
    return Schema(cols)


@_rec("categorical_to_one_hot")
def _r_cat2oh(schema, records, name):
    i = schema.index_of(name)
    cats = schema.columns[i].categories
    out = []
    for r in records:
        oh = [1 if r[i] == c else 0 for c in cats]
        out.append(r[:i] + oh + r[i + 1:])
    return out


# filter rows
@_step("filter")
def _s_filter(schema, predicate):
    return schema


@_rec("filter")
def _r_filter(schema, records, predicate):
    names = schema.names
    return [r for r in records if not predicate(dict(zip(names, r)))]


# math op on a double/int column
@_step("double_math_op")
def _s_math(schema, name, op, value):
    return schema


@_rec("double_math_op")
def _r_math(schema, records, name, op, value):
    i = schema.index_of(name)
    fn = {"add": lambda x: x + value, "subtract": lambda x: x - value,
          "multiply": lambda x: x * value, "divide": lambda x: x / value,
          "power": lambda x: x ** value, "min": lambda x: min(x, value),
          "max": lambda x: max(x, value)}[op]
    out = []
    for r in records:
        r = list(r)
        r[i] = fn(r[i])
        out.append(r)
    return out


# conditional replace
@_step("conditional_replace")
def _s_cond(schema, name, predicate, replacement):
    return schema


@_rec("conditional_replace")
def _r_cond(schema, records, name, predicate, replacement):
    i = schema.index_of(name)
    names = schema.names
    out = []
    for r in records:
        r = list(r)
        if predicate(dict(zip(names, r))):
            r[i] = replacement
        out.append(r)
    return out


# normalize (min-max or standardize) — computed over the dataset at execute time
@_step("normalize")
def _s_norm(schema, name, kind):
    return Schema([dataclasses.replace(c, type=ColumnType.DOUBLE)
                   if c.name == name else c for c in schema.columns])


@_rec("normalize")
def _r_norm(schema, records, name, kind):
    i = schema.index_of(name)
    vals = np.asarray([float(r[i]) for r in records])
    if kind == "minmax":
        lo, hi = vals.min(), vals.max()
        scaled = (vals - lo) / max(hi - lo, 1e-12)
    else:
        scaled = (vals - vals.mean()) / max(vals.std(), 1e-12)
    out = []
    for r, v in zip(records, scaled):
        r = list(r)
        r[i] = float(v)
        out.append(r)
    return out


# custom per-record function (escape hatch)
@_step("map_records")
def _s_map(schema, fn, new_schema=None):
    return new_schema or schema


@_rec("map_records")
def _r_map(schema, records, fn, new_schema=None):
    return [fn(list(r)) for r in records]


class TransformProcess:
    """Declarative transform pipeline (reference ``TransformProcess``)."""

    def __init__(self, initial_schema: Schema, steps: List[_Step]):
        self.initial_schema = initial_schema
        self.steps = steps

    def final_schema(self) -> Schema:
        s = self.initial_schema
        for st in self.steps:
            s = st.apply_schema(s)
        return s

    class Builder:
        def __init__(self, schema: Schema):
            self._schema = schema
            self._steps: List[_Step] = []

        def _add(self, kind, **args):
            self._steps.append(_Step(kind, args))
            return self

        def remove_columns(self, *names):
            return self._add("remove_columns", names=list(names))

        def remove_all_columns_except(self, *names):
            return self._add("remove_all_columns_except", names=list(names))

        def rename_column(self, old, new):
            return self._add("rename_column", old=old, new=new)

        def categorical_to_integer(self, name):
            return self._add("categorical_to_integer", name=name)

        def categorical_to_one_hot(self, name):
            return self._add("categorical_to_one_hot", name=name)

        def filter(self, predicate):
            """Remove rows where predicate(row_dict) is True."""
            return self._add("filter", predicate=predicate)

        def double_math_op(self, name, op, value):
            return self._add("double_math_op", name=name, op=op, value=value)

        def conditional_replace_value_transform(self, name, predicate, replacement):
            return self._add("conditional_replace", name=name, predicate=predicate,
                             replacement=replacement)

        def normalize(self, name, kind="standardize"):
            return self._add("normalize", name=name, kind=kind)

        def map_records(self, fn, new_schema=None):
            return self._add("map_records", fn=fn, new_schema=new_schema)

        def build(self) -> "TransformProcess":
            return TransformProcess(self._schema, list(self._steps))

    @staticmethod
    def builder(schema: Schema) -> "TransformProcess.Builder":
        return TransformProcess.Builder(schema)


class LocalTransformExecutor:
    """Reference ``org.datavec.local.transforms.LocalTransformExecutor``."""

    @staticmethod
    def execute(records: Iterable[List[Any]], tp: TransformProcess) -> List[List[Any]]:
        recs = [list(r) for r in records]
        schema = tp.initial_schema
        for st in tp.steps:
            recs = st.apply_records(schema, recs)
            schema = st.apply_schema(schema)
        return recs


# -------------------------------------------------- iterator bridge to training
class RecordReaderDataSetIterator(DataSetIterator):
    """Bridge records -> DataSet minibatches (reference
    ``RecordReaderDataSetIterator``): label column index + number of classes
    (classification, one-hot) or regression mode."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: int = -1, num_classes: Optional[int] = None,
                 regression: bool = False,
                 transform_process: Optional[TransformProcess] = None):
        records = [r for r in reader]
        if transform_process is not None:
            records = LocalTransformExecutor.execute(records, transform_process)
        self._features = []
        self._labels = []
        for r in records:
            li = label_index if label_index >= 0 else len(r) + label_index
            feats = [float(v) for i, v in enumerate(r) if i != li]
            self._features.append(feats)
            if regression:
                self._labels.append([float(r[li])])
            else:
                oh = [0.0] * num_classes
                oh[int(r[li])] = 1.0
                self._labels.append(oh)
        self._x = np.asarray(self._features, np.float32)
        self._y = np.asarray(self._labels, np.float32)
        self._batch = int(batch_size)
        self._pos = 0

    def has_next(self):
        return self._pos < len(self._x)

    def next(self) -> DataSet:
        sl = slice(self._pos, self._pos + self._batch)
        self._pos += self._batch
        return DataSet(self._x[sl], self._y[sl])

    def reset(self):
        self._pos = 0

    def batch(self):
        return self._batch

    def total_examples(self):
        return len(self._x)
