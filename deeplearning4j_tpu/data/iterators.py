"""DataSetIterator SPI + implementations.

Rebuild of the reference's iterator stack
(``org.nd4j.linalg.dataset.api.iterator.DataSetIterator``,
``org.deeplearning4j.datasets.iterator.*``): list/numpy-backed iterators and
the async prefetch wrapper (``AsyncDataSetIterator``) that overlaps host ETL
with device compute — on TPU this is host thread + ``jax.device_put``
double-buffering rather than the reference's workspace ring.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet


class DataSetIterator:
    """SPI: iterable of DataSet minibatches with reset + preprocessor hook."""

    pre_processor = None  # a Normalizer; applied to each batch if set

    def __iter__(self) -> Iterator[DataSet]:
        self.reset()
        return self

    def __next__(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        ds = self.next()
        if self.pre_processor is not None:
            ds = self.pre_processor.transform_dataset(ds)
        return ds

    # -- SPI --
    def has_next(self) -> bool:
        raise NotImplementedError

    def next(self) -> DataSet:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def batch(self) -> int:
        raise NotImplementedError

    def set_pre_processor(self, p) -> None:
        self.pre_processor = p


class ListDataSetIterator(DataSetIterator):
    """Iterate pre-built DataSets, optionally re-batched (reference
    ``ListDataSetIterator``)."""

    def __init__(self, datasets: Sequence[DataSet], batch_size: Optional[int] = None):
        if batch_size is not None:
            merged = DataSet.merge(list(datasets))
            self._batches = merged.batch_by(batch_size)
            self._batch_size = batch_size
        else:
            self._batches = list(datasets)
            self._batch_size = len(self._batches[0]) if self._batches else 0
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self._batches)

    def next(self) -> DataSet:
        ds = self._batches[self._pos]
        self._pos += 1
        return ds

    def reset(self) -> None:
        self._pos = 0

    def batch(self) -> int:
        return self._batch_size


class NumpyDataSetIterator(DataSetIterator):
    """Batch over in-memory arrays with optional shuffling each epoch."""

    def __init__(self, features: np.ndarray, labels: np.ndarray, batch_size: int,
                 shuffle: bool = False, seed: int = 0, drop_last: bool = False,
                 features_mask: Optional[np.ndarray] = None,
                 labels_mask: Optional[np.ndarray] = None):
        self.features = np.asarray(features)
        self.labels = np.asarray(labels)
        self.features_mask = features_mask
        self.labels_mask = labels_mask
        self._batch_size = int(batch_size)
        self.shuffle = shuffle
        self.drop_last = drop_last
        self._rng = np.random.default_rng(seed)
        self._order = np.arange(len(self.features))
        self._pos = 0
        if shuffle:
            self._rng.shuffle(self._order)

    def has_next(self) -> bool:
        remaining = len(self._order) - self._pos
        return remaining >= (self._batch_size if self.drop_last else 1)

    def next(self) -> DataSet:
        idx = self._order[self._pos:self._pos + self._batch_size]
        self._pos += len(idx)
        return DataSet(
            self.features[idx], self.labels[idx],
            None if self.features_mask is None else self.features_mask[idx],
            None if self.labels_mask is None else self.labels_mask[idx])

    def reset(self) -> None:
        self._pos = 0
        if self.shuffle:
            self._rng.shuffle(self._order)

    def batch(self) -> int:
        return self._batch_size


class ExistingDataSetIterator(DataSetIterator):
    """Wrap any python iterable of DataSets (reference
    ``ExistingDataSetIterator``)."""

    def __init__(self, iterable):
        self._iterable = iterable
        self._iter = None
        self._peek = None

    def reset(self) -> None:
        self._iter = iter(self._iterable)
        self._peek = None

    def has_next(self) -> bool:
        if self._iter is None:
            self.reset()
        if self._peek is None:
            try:
                self._peek = next(self._iter)
            except StopIteration:
                return False
        return True

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        ds, self._peek = self._peek, None
        return ds

    def batch(self) -> int:
        return -1


_SENTINEL = object()


def stop_aware_put(q: queue.Queue, item, stop: threading.Event,
                   tick: float = 0.1) -> bool:
    """Backpressure ``put`` that stays responsive to a stop event — a
    worker parked forever on a full queue could never be joined. Returns
    False when the stop fired first (item not enqueued). Shared by
    :class:`AsyncDataSetIterator` and
    :class:`~deeplearning4j_tpu.train.prefetch.DevicePrefetcher`."""
    while not stop.is_set():
        try:
            q.put(item, timeout=tick)
            return True
        except queue.Full:
            continue
    return False


def drain_and_join(q: queue.Queue, thread: threading.Thread,
                   tick: float = 0.1) -> None:
    """Join a queue-feeding worker, draining the queue so a worker blocked
    on ``put`` wakes within one tick — the one copy of the delicate
    teardown both background-feed stages share."""
    while thread.is_alive():
        try:
            q.get_nowait()
        except queue.Empty:
            pass
        thread.join(timeout=tick)


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch (reference ``AsyncDataSetIterator``):
    decouples host-side ETL from the training loop so the device never waits
    on data. ``queue_size`` is the prefetch depth (reference default 8).

    The worker honors a per-start stop event: ``reset()``/``close()``
    signal it to exit and join it instead of draining every remaining batch
    of the base iterator (the pre-ISSUE-4 reset cost one full pass of ETL
    work that was about to be thrown away). A worker ``_error`` surfaces on
    the consumer's **next** ``has_next()``/``next()`` — not only after the
    buffered batches and the sentinel — so a failed ETL stage stops the
    training loop at the failure, not several batches later.
    """

    def __init__(self, base: DataSetIterator, queue_size: int = 8):
        self.base = base
        self.queue_size = max(1, int(queue_size))
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._stop: Optional[threading.Event] = None
        self._peek = None
        self._error: Optional[BaseException] = None
        self._exhausted = False  # sentinel already consumed by has_next

    def _start(self):
        self._queue = queue.Queue(maxsize=self.queue_size)
        self._error = None
        self._exhausted = False
        stop = self._stop = threading.Event()
        q = self._queue

        def worker():
            try:
                self.base.reset()
                while not stop.is_set() and self.base.has_next():
                    if not stop_aware_put(q, self.base.next(), stop):
                        return
            except BaseException as e:  # surfaced on the consumer side
                self._error = e
            finally:
                stop_aware_put(q, _SENTINEL, stop)

        self._thread = threading.Thread(target=worker, daemon=True,
                                        name="async-dataset-iterator")
        self._thread.start()

    def _shutdown_worker(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        drain_and_join(self._queue, self._thread)
        self._thread = None

    def reset(self) -> None:
        self._shutdown_worker()
        self._start()
        self._peek = None

    def close(self) -> None:
        """Stop the worker without restarting it (end-of-use teardown; a
        later ``reset()`` starts fresh). Safe to call at any point."""
        self._shutdown_worker()
        self._queue = None
        self._peek = None

    def _raise_pending(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            self._exhausted = True
            # stop+join first: a worker still parked on put() (full queue)
            # must not outlive the raise — nothing will consume after it
            self._shutdown_worker()
            raise err

    def has_next(self) -> bool:
        if self._queue is None:
            self.reset()
        if self._peek is None:
            if self._exhausted:
                return False
            # a fault that already happened surfaces NOW — buffered batches
            # staged behind it are discarded, not trained
            self._raise_pending()
            item = self._queue.get()
            if item is _SENTINEL:
                self._exhausted = True
                self._raise_pending()
                return False
            self._peek = item
        return True

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        ds, self._peek = self._peek, None
        return ds

    def batch(self) -> int:
        return self.base.batch()
