"""Image loading + augmentation pipeline.

Rebuild of datavec-data-image: ``ImageRecordReader`` (directory tree ->
labelled image records, label = parent directory, the reference's
``ParentPathLabelGenerator`` convention) and the ``ImageTransform``
augmentation SPI (``org.datavec.image.transform.*``: crop, flip, rotate,
warp, scale, resize, random crop, pipeline-with-probabilities).

The reference decodes via OpenCV JavaCPP presets (``NativeImageLoader``);
here decode is TF's native JPEG/PNG ops (CPU, offline) with the C++ host
pipeline (``native/image_pipeline.cpp``) available for the u8->f32
normalize/crop hot path. Transforms operate on NHWC float numpy arrays —
host-side ETL, overlapped with device compute by ``AsyncDataSetIterator``.
"""

from __future__ import annotations

import glob
import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import DataSetIterator


# --------------------------------------------------------------- transforms
_DEFAULT_RNG = np.random.default_rng(0)


class ImageTransform:
    """SPI: ``transform(image, rng) -> image`` on one HWC float array."""

    def transform(self, image: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        raise NotImplementedError

    def __call__(self, image, rng=None):
        # shared stateful generator: deterministic across runs, but DIFFERENT
        # per call (a fresh default_rng(0) per call would repeat the same
        # "random" decision for every image)
        return self.transform(image, rng if rng is not None else _DEFAULT_RNG)


class CropImageTransform(ImageTransform):
    """Crop fixed margins (reference ``CropImageTransform``)."""

    def __init__(self, top: int, left: int = None, bottom: int = None, right: int = None):
        self.top = top
        self.left = top if left is None else left
        self.bottom = top if bottom is None else bottom
        self.right = top if right is None else right

    def transform(self, image, rng):
        h, w = image.shape[:2]
        return image[self.top:h - self.bottom, self.left:w - self.right]


class RandomCropTransform(ImageTransform):
    """Random crop to (height, width) (reference ``RandomCropTransform``)."""

    def __init__(self, height: int, width: int):
        self.height, self.width = height, width

    def transform(self, image, rng):
        h, w = image.shape[:2]
        if h < self.height or w < self.width:
            pad_h, pad_w = max(0, self.height - h), max(0, self.width - w)
            image = np.pad(image, ((0, pad_h), (0, pad_w), (0, 0)))
            h, w = image.shape[:2]
        y = int(rng.integers(0, h - self.height + 1))
        x = int(rng.integers(0, w - self.width + 1))
        return image[y:y + self.height, x:x + self.width]


class FlipImageTransform(ImageTransform):
    """Flip (reference ``FlipImageTransform``): mode 0 = vertical,
    1 = horizontal, -1 = both, None = random horizontal."""

    def __init__(self, mode: Optional[int] = None):
        self.mode = mode

    def transform(self, image, rng):
        mode = self.mode
        if mode is None:
            if rng.random() < 0.5:
                return image
            mode = 1
        if mode in (1, -1):
            image = image[:, ::-1]
        if mode in (0, -1):
            image = image[::-1]
        return np.ascontiguousarray(image)


class RotateImageTransform(ImageTransform):
    """Rotate by ``angle`` degrees (± ``random_angle`` jitter if given)
    about the centre (reference ``RotateImageTransform``)."""

    def __init__(self, angle: float, random_angle: float = 0.0):
        self.angle, self.random_angle = angle, random_angle

    def transform(self, image, rng):
        from scipy.ndimage import rotate
        a = self.angle
        if self.random_angle:
            a = a + rng.uniform(-self.random_angle, self.random_angle)
        return rotate(image, a, axes=(1, 0), reshape=False, order=1,
                      mode="nearest").astype(image.dtype)


class ScaleImageTransform(ImageTransform):
    """Scale height/width by a (possibly jittered) factor (reference
    ``ScaleImageTransform``)."""

    def __init__(self, scale: float, random_delta: float = 0.0):
        self.scale, self.random_delta = scale, random_delta

    def transform(self, image, rng):
        s = self.scale
        if self.random_delta:
            s = s + rng.uniform(-self.random_delta, self.random_delta)
        h, w = image.shape[:2]
        return _resize(image, max(1, int(round(h * s))), max(1, int(round(w * s))))


class ResizeImageTransform(ImageTransform):
    """Resize to fixed (height, width) (reference ``ResizeImageTransform``)."""

    def __init__(self, height: int, width: int):
        self.height, self.width = height, width

    def transform(self, image, rng):
        return _resize(image, self.height, self.width)


class WarpImageTransform(ImageTransform):
    """Random perspective-ish warp: jitter the 4 corners by up to ``delta``
    pixels and resample (reference ``WarpImageTransform``)."""

    def __init__(self, delta: float):
        self.delta = delta

    def transform(self, image, rng):
        from scipy.ndimage import map_coordinates
        h, w = image.shape[:2]
        d = self.delta
        # corner displacements
        dy = rng.uniform(-d, d, 4)
        dx = rng.uniform(-d, d, 4)
        yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
        fy, fx = yy / max(h - 1, 1), xx / max(w - 1, 1)
        # bilinear blend of corner offsets
        off_y = (1 - fy) * (1 - fx) * dy[0] + (1 - fy) * fx * dy[1] \
            + fy * (1 - fx) * dy[2] + fy * fx * dy[3]
        off_x = (1 - fy) * (1 - fx) * dx[0] + (1 - fy) * fx * dx[1] \
            + fy * (1 - fx) * dx[2] + fy * fx * dx[3]
        out = np.empty_like(image)
        for c in range(image.shape[2]):
            out[..., c] = map_coordinates(image[..., c], [yy + off_y, xx + off_x],
                                          order=1, mode="nearest")
        return out


class PipelineImageTransform(ImageTransform):
    """Sequence of (transform, probability) pairs, optionally shuffled
    (reference ``PipelineImageTransform``)."""

    def __init__(self, transforms: Sequence, shuffle: bool = False):
        self.entries: List[Tuple[ImageTransform, float]] = [
            t if isinstance(t, tuple) else (t, 1.0) for t in transforms]
        self.shuffle = shuffle

    def transform(self, image, rng):
        entries = list(self.entries)
        if self.shuffle:
            rng.shuffle(entries)
        for t, p in entries:
            if p >= 1.0 or rng.random() < p:
                image = t.transform(image, rng)
        return image


def _resize(image: np.ndarray, h: int, w: int) -> np.ndarray:
    """Bilinear resize via scipy zoom (OpenCV-free)."""
    from scipy.ndimage import zoom
    zh, zw = h / image.shape[0], w / image.shape[1]
    out = zoom(image, (zh, zw, 1), order=1)
    # zoom rounding can be off by one; crop/pad to exact
    out = out[:h, :w]
    if out.shape[0] < h or out.shape[1] < w:
        out = np.pad(out, ((0, h - out.shape[0]), (0, w - out.shape[1]), (0, 0)),
                     mode="edge")
    return out.astype(image.dtype)


# ------------------------------------------------------------ record reader
class ImageRecordReader:
    """Reads a directory tree of images; label = parent directory name
    (reference ``ImageRecordReader`` + ``ParentPathLabelGenerator``).
    Yields (image HWC float32 in [0,255], label index)."""

    EXTENSIONS = (".jpg", ".jpeg", ".png", ".bmp", ".JPEG", ".JPG", ".PNG", ".npy")

    def __init__(self, height: int, width: int, channels: int = 3,
                 transform: Optional[ImageTransform] = None, seed: int = 0):
        self.height, self.width, self.channels = height, width, channels
        self.transform = transform
        self.labels: List[str] = []
        self._files: List[Tuple[str, int]] = []
        self._pos = 0
        self._rng = np.random.default_rng(seed)

    def initialize(self, root: str) -> "ImageRecordReader":
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.labels = classes
        self._files = []
        for ci, c in enumerate(classes):
            for f in sorted(glob.glob(os.path.join(root, c, "**", "*"),
                                      recursive=True)):
                if f.endswith(self.EXTENSIONS):
                    self._files.append((f, ci))
        self._pos = 0
        return self

    def _decode(self, path: str) -> np.ndarray:
        if path.endswith((".npy",)):
            img = np.load(path)
        else:
            import tensorflow as tf
            img = tf.io.decode_image(tf.io.read_file(path),
                                     channels=self.channels).numpy()
        return img.astype(np.float32)

    def has_next(self) -> bool:
        return self._pos < len(self._files)

    def next(self) -> Tuple[np.ndarray, int]:
        path, label = self._files[self._pos]
        self._pos += 1
        img = self._decode(path)
        if img.ndim == 2:
            img = img[..., None]
        if self.transform is not None:
            img = self.transform.transform(img, self._rng)
        if img.shape[:2] != (self.height, self.width):
            img = _resize(img, self.height, self.width)
        return img, label

    def reset(self) -> None:
        self._pos = 0


class ImageRecordReaderDataSetIterator(DataSetIterator):
    """Bridge ImageRecordReader -> DataSet minibatches (the reference's
    ``RecordReaderDataSetIterator`` specialized for images)."""

    def __init__(self, reader: ImageRecordReader, batch_size: int,
                 num_classes: Optional[int] = None, scale: float = 1.0 / 255.0):
        self.reader = reader
        self.batch_size = batch_size
        self.num_classes = num_classes or len(reader.labels)
        self.scale = scale

    def has_next(self) -> bool:
        return self.reader.has_next()

    def next(self) -> DataSet:
        xs, ys = [], []
        while self.reader.has_next() and len(xs) < self.batch_size:
            img, lab = self.reader.next()
            xs.append(img * self.scale)
            ys.append(lab)
        onehot = np.zeros((len(ys), self.num_classes), np.float32)
        onehot[np.arange(len(ys)), ys] = 1.0
        return DataSet(np.stack(xs).astype(np.float32), onehot)

    def reset(self) -> None:
        self.reader.reset()

    def batch(self) -> int:
        return self.batch_size
