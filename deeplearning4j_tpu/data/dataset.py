"""DataSet / MultiDataSet containers (reference
``org.nd4j.linalg.dataset.DataSet`` / ``MultiDataSet``): features + labels +
optional masks, with save/load and utility ops. Arrays are host numpy — device
transfer happens at the jitted-step boundary (and is overlapped by
``AsyncDataSetIterator``)."""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass
class DataSet:
    features: np.ndarray
    labels: np.ndarray
    features_mask: Optional[np.ndarray] = None
    labels_mask: Optional[np.ndarray] = None

    def __post_init__(self):
        self.features = np.asarray(self.features)
        self.labels = np.asarray(self.labels)

    def __len__(self) -> int:
        return self.features.shape[0]

    def num_examples(self) -> int:
        return len(self)

    def split_test_and_train(self, n_train: int) -> Tuple["DataSet", "DataSet"]:
        return self.range(0, n_train), self.range(n_train, len(self))

    def range(self, start: int, end: int) -> "DataSet":
        sl = slice(start, end)
        return DataSet(
            self.features[sl], self.labels[sl],
            None if self.features_mask is None else self.features_mask[sl],
            None if self.labels_mask is None else self.labels_mask[sl])

    def shuffle(self, seed: Optional[int] = None) -> None:
        rng = np.random.default_rng(seed)
        idx = rng.permutation(len(self))
        self.features = self.features[idx]
        self.labels = self.labels[idx]
        if self.features_mask is not None:
            self.features_mask = self.features_mask[idx]
        if self.labels_mask is not None:
            self.labels_mask = self.labels_mask[idx]

    def batch_by(self, batch_size: int) -> List["DataSet"]:
        return [self.range(i, min(i + batch_size, len(self)))
                for i in range(0, len(self), batch_size)]

    @staticmethod
    def merge(datasets: Sequence["DataSet"]) -> "DataSet":
        return DataSet(
            np.concatenate([d.features for d in datasets]),
            np.concatenate([d.labels for d in datasets]),
            _cat_masks([d.features_mask for d in datasets]),
            _cat_masks([d.labels_mask for d in datasets]))

    def save(self, path: str) -> None:
        arrays = {"features": self.features, "labels": self.labels}
        if self.features_mask is not None:
            arrays["features_mask"] = self.features_mask
        if self.labels_mask is not None:
            arrays["labels_mask"] = self.labels_mask
        np.savez_compressed(path, **arrays)

    @staticmethod
    def load(path: str) -> "DataSet":
        z = np.load(path)
        return DataSet(z["features"], z["labels"],
                       z["features_mask"] if "features_mask" in z else None,
                       z["labels_mask"] if "labels_mask" in z else None)


def _cat_masks(masks):
    if all(m is None for m in masks):
        return None
    if any(m is None for m in masks):
        raise ValueError("Cannot merge DataSets with mixed mask presence")
    return np.concatenate(masks)


@dataclasses.dataclass
class MultiDataSet:
    """Multiple feature/label arrays (reference ``MultiDataSet``) — feeds
    ComputationGraph's multi-input/multi-output training."""

    features: List[np.ndarray]
    labels: List[np.ndarray]
    features_masks: Optional[List[Optional[np.ndarray]]] = None
    labels_masks: Optional[List[Optional[np.ndarray]]] = None

    def __post_init__(self):
        self.features = [np.asarray(f) for f in self.features]
        self.labels = [np.asarray(l) for l in self.labels]

    def __len__(self) -> int:
        return self.features[0].shape[0]

    def num_examples(self) -> int:
        return len(self)
