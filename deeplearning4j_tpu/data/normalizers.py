"""Data normalizers (reference
``org.nd4j.linalg.dataset.api.preprocessor``): ``NormalizerStandardize``
(zero-mean/unit-variance), ``NormalizerMinMaxScaler``,
``ImagePreProcessingScaler`` (pixel range map), plus ``VGG16ImagePreProcessor``
(mean subtraction). fit/transform/revert + serialization, as in the
reference."""

from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet


class Normalizer:
    def fit(self, data) -> "Normalizer":
        """``data``: DataSet or DataSetIterator."""
        raise NotImplementedError

    def transform(self, features: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def revert(self, features: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def transform_dataset(self, ds: DataSet) -> DataSet:
        return DataSet(self.transform(ds.features), ds.labels,
                       ds.features_mask, ds.labels_mask)

    def _iter_features(self, data):
        if isinstance(data, DataSet):
            yield data.features
        else:
            data.reset()
            for b in data:
                yield b.features
            data.reset()

    def save(self, path: str) -> None:
        np.savez(path, kind=type(self).__name__, **self._state())

    @staticmethod
    def load(path: str) -> "Normalizer":
        z = np.load(path, allow_pickle=False)
        kind = str(z["kind"])
        cls = {c.__name__: c for c in (NormalizerStandardize, NormalizerMinMaxScaler,
                                       ImagePreProcessingScaler, VGG16ImagePreProcessor)}[kind]
        obj = cls.__new__(cls)
        obj._load_state(z)
        return obj

    def _state(self) -> dict:
        return {}

    def _load_state(self, z) -> None:
        pass


class NormalizerStandardize(Normalizer):
    def __init__(self):
        self.mean: Optional[np.ndarray] = None
        self.std: Optional[np.ndarray] = None

    def fit(self, data):
        n, s, s2 = 0, 0.0, 0.0
        for f in self._iter_features(data):
            f = f.reshape(len(f), -1).astype(np.float64)
            n += f.shape[0]
            s = s + f.sum(0)
            s2 = s2 + (f ** 2).sum(0)
        self.mean = (s / n).astype(np.float32)
        var = s2 / n - (s / n) ** 2
        self.std = np.sqrt(np.maximum(var, 1e-12)).astype(np.float32)
        return self

    def transform(self, features):
        shape = features.shape
        flat = features.reshape(len(features), -1)
        return ((flat - self.mean) / self.std).reshape(shape).astype(np.float32)

    def revert(self, features):
        shape = features.shape
        flat = features.reshape(len(features), -1)
        return (flat * self.std + self.mean).reshape(shape)

    def _state(self):
        return {"mean": self.mean, "std": self.std}

    def _load_state(self, z):
        self.mean, self.std = z["mean"], z["std"]


class NormalizerMinMaxScaler(Normalizer):
    def __init__(self, min_range: float = 0.0, max_range: float = 1.0):
        self.min_range, self.max_range = float(min_range), float(max_range)
        self.data_min: Optional[np.ndarray] = None
        self.data_max: Optional[np.ndarray] = None

    def fit(self, data):
        mn, mx = None, None
        for f in self._iter_features(data):
            f = f.reshape(len(f), -1)
            bmn, bmx = f.min(0), f.max(0)
            mn = bmn if mn is None else np.minimum(mn, bmn)
            mx = bmx if mx is None else np.maximum(mx, bmx)
        self.data_min, self.data_max = mn.astype(np.float32), mx.astype(np.float32)
        return self

    def transform(self, features):
        shape = features.shape
        flat = features.reshape(len(features), -1)
        rng = np.maximum(self.data_max - self.data_min, 1e-12)
        scaled = (flat - self.data_min) / rng
        out = scaled * (self.max_range - self.min_range) + self.min_range
        return out.reshape(shape).astype(np.float32)

    def revert(self, features):
        shape = features.shape
        flat = features.reshape(len(features), -1)
        rng = np.maximum(self.data_max - self.data_min, 1e-12)
        return (((flat - self.min_range) / (self.max_range - self.min_range)) * rng
                + self.data_min).reshape(shape)

    def _state(self):
        return {"data_min": self.data_min, "data_max": self.data_max,
                "ranges": np.array([self.min_range, self.max_range])}

    def _load_state(self, z):
        self.data_min, self.data_max = z["data_min"], z["data_max"]
        self.min_range, self.max_range = z["ranges"]


class ImagePreProcessingScaler(Normalizer):
    """Pixel scaler (reference ``ImagePreProcessingScaler``): maps [0, 255]
    to [min, max]; no fit needed."""

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0, max_pixel: float = 255.0):
        self.min_range, self.max_range, self.max_pixel = min_range, max_range, max_pixel

    def fit(self, data):
        return self

    def transform(self, features):
        return (features / self.max_pixel * (self.max_range - self.min_range)
                + self.min_range).astype(np.float32)

    def revert(self, features):
        return (features - self.min_range) / (self.max_range - self.min_range) * self.max_pixel

    def _state(self):
        return {"ranges": np.array([self.min_range, self.max_range, self.max_pixel])}

    def _load_state(self, z):
        self.min_range, self.max_range, self.max_pixel = z["ranges"]


class VGG16ImagePreProcessor(Normalizer):
    """Subtract ImageNet channel means (reference ``VGG16ImagePreProcessor``).
    NHWC layout."""

    MEANS = np.array([123.68, 116.779, 103.939], np.float32)

    def fit(self, data):
        return self

    def transform(self, features):
        return (features - self.MEANS).astype(np.float32)

    def revert(self, features):
        return features + self.MEANS
