"""Regression evaluation (reference
``org.nd4j.evaluation.regression.RegressionEvaluation``): per-column MSE, MAE,
RMSE, R², Pearson correlation."""

from __future__ import annotations

from typing import Optional

import numpy as np


class RegressionEvaluation:
    def __init__(self, n_columns: Optional[int] = None):
        self.n = 0
        self._init_cols(n_columns)

    def _init_cols(self, c):
        self.n_columns = c
        if c:
            z = np.zeros(c, np.float64)
            self.sum_err_sq, self.sum_abs_err = z.copy(), z.copy()
            self.sum_label, self.sum_label_sq = z.copy(), z.copy()
            self.sum_pred, self.sum_pred_sq = z.copy(), z.copy()
            self.sum_label_pred = z.copy()

    def eval(self, labels: np.ndarray, predictions: np.ndarray,
             mask: Optional[np.ndarray] = None) -> None:
        labels = np.asarray(labels, np.float64)
        predictions = np.asarray(predictions, np.float64)
        if labels.ndim == 3:
            labels = labels.reshape(-1, labels.shape[-1])
            predictions = predictions.reshape(-1, predictions.shape[-1])
            if mask is not None:
                keep = np.asarray(mask).reshape(-1) > 0
                labels, predictions = labels[keep], predictions[keep]
        if labels.ndim == 1:
            labels, predictions = labels[:, None], predictions[:, None]
        if self.n_columns is None:
            self._init_cols(labels.shape[1])
        err = predictions - labels
        self.n += labels.shape[0]
        self.sum_err_sq += (err ** 2).sum(0)
        self.sum_abs_err += np.abs(err).sum(0)
        self.sum_label += labels.sum(0)
        self.sum_label_sq += (labels ** 2).sum(0)
        self.sum_pred += predictions.sum(0)
        self.sum_pred_sq += (predictions ** 2).sum(0)
        self.sum_label_pred += (labels * predictions).sum(0)

    def mean_squared_error(self, col: int = 0) -> float:
        return float(self.sum_err_sq[col] / max(1, self.n))

    def mean_absolute_error(self, col: int = 0) -> float:
        return float(self.sum_abs_err[col] / max(1, self.n))

    def root_mean_squared_error(self, col: int = 0) -> float:
        return float(np.sqrt(self.mean_squared_error(col)))

    def r_squared(self, col: int = 0) -> float:
        ss_tot = self.sum_label_sq[col] - self.sum_label[col] ** 2 / max(1, self.n)
        ss_res = self.sum_err_sq[col]
        return float(1.0 - ss_res / ss_tot) if ss_tot > 0 else float("nan")

    def pearson_correlation(self, col: int = 0) -> float:
        n = max(1, self.n)
        cov = self.sum_label_pred[col] - self.sum_label[col] * self.sum_pred[col] / n
        var_l = self.sum_label_sq[col] - self.sum_label[col] ** 2 / n
        var_p = self.sum_pred_sq[col] - self.sum_pred[col] ** 2 / n
        denom = np.sqrt(var_l * var_p)
        return float(cov / denom) if denom > 0 else float("nan")

    def average_mean_squared_error(self) -> float:
        return float(np.mean(self.sum_err_sq / max(1, self.n)))

    def average_r_squared(self) -> float:
        return float(np.nanmean([self.r_squared(c) for c in range(self.n_columns)]))

    def stats(self) -> str:
        lines = ["=================Regression Evaluation=================",
                 f" columns: {self.n_columns}, examples: {self.n}",
                 f"{'col':>5}{'MSE':>14}{'MAE':>14}{'RMSE':>14}{'R^2':>14}{'corr':>14}"]
        for c in range(self.n_columns or 0):
            lines.append(f"{c:>5}{self.mean_squared_error(c):>14.6f}"
                         f"{self.mean_absolute_error(c):>14.6f}"
                         f"{self.root_mean_squared_error(c):>14.6f}"
                         f"{self.r_squared(c):>14.6f}{self.pearson_correlation(c):>14.6f}")
        return "\n".join(lines)
