"""ROC / AUC evaluation (reference ``org.nd4j.evaluation.classification.ROC``,
``ROCBinary``, ``ROCMultiClass``). ``threshold_steps=0`` = exact mode (all
scores kept, exact AUROC/AUPRC, the reference's beta4+ default)."""

from __future__ import annotations

from typing import List, Optional

import numpy as np


class ROC:
    """Binary ROC: positive-class probability vs 0/1 label."""

    def __init__(self, threshold_steps: int = 0):
        self.threshold_steps = int(threshold_steps)
        self._scores: List[np.ndarray] = []
        self._labels: List[np.ndarray] = []

    def eval(self, labels: np.ndarray, predictions: np.ndarray) -> None:
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 2 and labels.shape[1] == 2:  # one-hot binary
            labels = labels[:, 1]
            predictions = predictions[:, 1]
        elif labels.ndim == 2 and labels.shape[1] == 1:
            labels, predictions = labels[:, 0], predictions[:, 0]
        self._labels.append(labels.astype(np.float64).ravel())
        self._scores.append(predictions.astype(np.float64).ravel())

    def _collect(self):
        y = np.concatenate(self._labels) if self._labels else np.zeros(0)
        s = np.concatenate(self._scores) if self._scores else np.zeros(0)
        if self.threshold_steps > 0:
            s = np.round(s * self.threshold_steps) / self.threshold_steps
        return y, s

    def roc_curve(self):
        """Returns (fpr, tpr, thresholds) exact curve."""
        y, s = self._collect()
        order = np.argsort(-s, kind="stable")
        y, s = y[order], s[order]
        tps = np.cumsum(y)
        fps = np.cumsum(1 - y)
        # keep last point per distinct threshold
        distinct = np.r_[np.diff(s) != 0, True]
        tps, fps, thr = tps[distinct], fps[distinct], s[distinct]
        P, N = max(tps[-1], 1e-12) if len(tps) else 1, max(fps[-1], 1e-12) if len(fps) else 1
        tpr = np.r_[0.0, tps / P]
        fpr = np.r_[0.0, fps / N]
        return fpr, tpr, np.r_[np.inf, thr]

    def calculate_auc(self) -> float:
        fpr, tpr, _ = self.roc_curve()
        return float(np.trapezoid(tpr, fpr))

    def calculate_auprc(self) -> float:
        y, s = self._collect()
        order = np.argsort(-s, kind="stable")
        y = y[order]
        tps = np.cumsum(y)
        precision = tps / np.arange(1, len(y) + 1)
        recall = tps / max(tps[-1] if len(tps) else 1, 1e-12)
        return float(np.trapezoid(precision, recall))

    def stats(self) -> str:
        return (f"ROC (exact={self.threshold_steps == 0}): "
                f"AUROC={self.calculate_auc():.4f}, AUPRC={self.calculate_auprc():.4f}")


class ROCBinary:
    """Independent binary ROC per output column (multi-label)."""

    def __init__(self, threshold_steps: int = 0):
        self.threshold_steps = threshold_steps
        self._per_col: Optional[List[ROC]] = None

    def eval(self, labels: np.ndarray, predictions: np.ndarray) -> None:
        labels, predictions = np.asarray(labels), np.asarray(predictions)
        if labels.ndim == 1:
            labels, predictions = labels[:, None], predictions[:, None]
        if self._per_col is None:
            self._per_col = [ROC(self.threshold_steps) for _ in range(labels.shape[1])]
        for c, roc in enumerate(self._per_col):
            roc._labels.append(labels[:, c].astype(np.float64))
            roc._scores.append(predictions[:, c].astype(np.float64))

    def calculate_auc(self, col: int = 0) -> float:
        return self._per_col[col].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self._per_col]))


class ROCMultiClass:
    """One-vs-all ROC per class (reference ``ROCMultiClass``)."""

    def __init__(self, threshold_steps: int = 0):
        self.threshold_steps = threshold_steps
        self._per_class: Optional[List[ROC]] = None

    def eval(self, labels: np.ndarray, predictions: np.ndarray) -> None:
        labels, predictions = np.asarray(labels), np.asarray(predictions)
        n_classes = predictions.shape[-1]
        if labels.ndim == 1:
            labels = np.eye(n_classes)[labels.astype(np.int64)]
        if self._per_class is None:
            self._per_class = [ROC(self.threshold_steps) for _ in range(n_classes)]
        for c, roc in enumerate(self._per_class):
            roc._labels.append(labels[:, c].astype(np.float64))
            roc._scores.append(predictions[:, c].astype(np.float64))

    def calculate_auc(self, cls: int) -> float:
        return self._per_class[cls].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self._per_class]))
