"""Probability-calibration evaluation (reference
``org.nd4j.evaluation.classification.EvaluationCalibration``): reliability
diagram bins, expected calibration error, residual-probability histogram."""

from __future__ import annotations

import numpy as np


class EvaluationCalibration:
    def __init__(self, reliability_bins: int = 10):
        self.bins = int(reliability_bins)
        self.bin_counts = np.zeros(self.bins, np.int64)
        self.bin_correct = np.zeros(self.bins, np.int64)
        self.bin_prob_sum = np.zeros(self.bins, np.float64)

    def eval(self, labels: np.ndarray, predictions: np.ndarray) -> None:
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        true_idx = labels.argmax(-1) if labels.ndim == 2 else labels.astype(np.int64)
        pred_idx = predictions.argmax(-1)
        conf = predictions.max(-1)
        idx = np.clip((conf * self.bins).astype(np.int64), 0, self.bins - 1)
        np.add.at(self.bin_counts, idx, 1)
        np.add.at(self.bin_correct, idx, (pred_idx == true_idx).astype(np.int64))
        np.add.at(self.bin_prob_sum, idx, conf)

    def reliability_diagram(self):
        """Returns (mean_confidence, accuracy, count) per bin."""
        with np.errstate(invalid="ignore"):
            mean_conf = np.divide(self.bin_prob_sum, self.bin_counts,
                                  out=np.zeros(self.bins), where=self.bin_counts > 0)
            acc = np.divide(self.bin_correct, self.bin_counts,
                            out=np.zeros(self.bins), where=self.bin_counts > 0)
        return mean_conf, acc, self.bin_counts.copy()

    def expected_calibration_error(self) -> float:
        mean_conf, acc, counts = self.reliability_diagram()
        total = counts.sum()
        if total == 0:
            return float("nan")
        return float(np.sum(counts / total * np.abs(acc - mean_conf)))

    def stats(self) -> str:
        mean_conf, acc, counts = self.reliability_diagram()
        lines = ["============Calibration Evaluation============",
                 f" ECE: {self.expected_calibration_error():.4f}",
                 f"{'bin':>5}{'conf':>10}{'acc':>10}{'count':>10}"]
        for b in range(self.bins):
            lines.append(f"{b:>5}{mean_conf[b]:>10.4f}{acc[b]:>10.4f}{counts[b]:>10}")
        return "\n".join(lines)
