"""Classification evaluation.

Rebuild of upstream ``org.nd4j.evaluation.classification.Evaluation``:
confusion matrix, accuracy, per-class & averaged precision/recall/F1,
Matthews correlation, top-N accuracy, pretty ``stats()`` report.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


class Evaluation:
    def __init__(self, num_classes: Optional[int] = None,
                 labels: Optional[List[str]] = None, top_n: int = 1):
        self.num_classes = num_classes
        self.label_names = labels
        self.top_n = max(1, int(top_n))
        self.confusion: Optional[np.ndarray] = None
        self.top_n_correct = 0
        self.total = 0

    def _ensure(self, n: int):
        if self.confusion is None:
            self.num_classes = self.num_classes or n
            self.confusion = np.zeros((self.num_classes, self.num_classes), np.int64)

    def eval(self, labels: np.ndarray, predictions: np.ndarray,
             mask: Optional[np.ndarray] = None) -> None:
        """labels: one-hot (N,C) / int (N,); predictions: probs (N,C).
        Rank-3 sequence outputs are flattened over time with the mask applied
        (reference ``evalTimeSeries``)."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if predictions.ndim == 3:
            b, t, c = predictions.shape
            predictions = predictions.reshape(b * t, c)
            labels = labels.reshape(b * t, -1) if labels.ndim == 3 else labels.reshape(b * t)
            if mask is not None:
                keep = np.asarray(mask).reshape(b * t) > 0
                predictions, labels = predictions[keep], labels[keep]
        n_classes = predictions.shape[-1]
        self._ensure(n_classes)
        true_idx = labels.argmax(-1) if labels.ndim == 2 else labels.astype(np.int64)
        pred_idx = predictions.argmax(-1)
        np.add.at(self.confusion, (true_idx, pred_idx), 1)
        self.total += len(true_idx)
        if self.top_n > 1:
            top = np.argsort(-predictions, axis=-1)[:, :self.top_n]
            self.top_n_correct += int((top == true_idx[:, None]).any(-1).sum())
        else:
            self.top_n_correct += int((pred_idx == true_idx).sum())

    # ---- metrics ----
    def accuracy(self) -> float:
        if self.confusion is None or self.total == 0:
            return float("nan")
        return float(np.trace(self.confusion)) / self.total

    def top_n_accuracy(self) -> float:
        return self.top_n_correct / max(1, self.total)

    def _tp(self):
        return np.diag(self.confusion).astype(np.float64)

    def precision(self, cls: Optional[int] = None) -> float:
        col = self.confusion.sum(0).astype(np.float64)
        p = np.divide(self._tp(), col, out=np.zeros_like(col), where=col > 0)
        return float(p[cls]) if cls is not None else float(p[col > 0].mean() if (col > 0).any() else 0.0)

    def recall(self, cls: Optional[int] = None) -> float:
        row = self.confusion.sum(1).astype(np.float64)
        r = np.divide(self._tp(), row, out=np.zeros_like(row), where=row > 0)
        return float(r[cls]) if cls is not None else float(r[row > 0].mean() if (row > 0).any() else 0.0)

    def f1(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            p, r = self.precision(cls), self.recall(cls)
            return 2 * p * r / (p + r) if p + r > 0 else 0.0
        col = self.confusion.sum(0).astype(np.float64)
        row = self.confusion.sum(1).astype(np.float64)
        tp = self._tp()
        p = np.divide(tp, col, out=np.zeros_like(col), where=col > 0)
        r = np.divide(tp, row, out=np.zeros_like(row), where=row > 0)
        f = np.divide(2 * p * r, p + r, out=np.zeros_like(p), where=(p + r) > 0)
        valid = (row > 0) | (col > 0)
        return float(f[valid].mean() if valid.any() else 0.0)

    def matthews_correlation(self) -> float:
        """Multiclass MCC (Gorodkin R_k)."""
        C = self.confusion.astype(np.float64)
        t = C.sum()
        s = np.trace(C)
        row, col = C.sum(1), C.sum(0)
        cov_xy = s * t - row @ col
        cov_xx = t * t - row @ row
        cov_yy = t * t - col @ col
        denom = np.sqrt(cov_xx * cov_yy)
        return float(cov_xy / denom) if denom > 0 else 0.0

    def confusion_matrix(self) -> np.ndarray:
        return self.confusion.copy() if self.confusion is not None else np.zeros((0, 0))

    def merge(self, other: "Evaluation") -> None:
        """Combine partial evaluations (reference: distributed eval merge)."""
        if other.confusion is None:
            return
        self._ensure(other.confusion.shape[0])
        self.confusion += other.confusion
        self.total += other.total
        self.top_n_correct += other.top_n_correct

    def stats(self) -> str:
        if self.confusion is None:
            return "Evaluation: no data"
        names = self.label_names or [str(i) for i in range(self.num_classes)]
        lines = [
            "========================Evaluation Metrics========================",
            f" # of classes:    {self.num_classes}",
            f" Accuracy:        {self.accuracy():.4f}",
            f" Precision:       {self.precision():.4f}",
            f" Recall:          {self.recall():.4f}",
            f" F1 Score:        {self.f1():.4f}",
        ]
        if self.top_n > 1:
            lines.append(f" Top-{self.top_n} Accuracy: {self.top_n_accuracy():.4f}")
        lines.append("")
        lines.append("=========================Confusion Matrix=========================")
        width = max(5, max(len(n) for n in names) + 1)
        header = " " * width + "".join(f"{n:>{width}}" for n in names)
        lines.append(header)
        for i, n in enumerate(names):
            lines.append(f"{n:>{width}}" + "".join(
                f"{self.confusion[i, j]:>{width}}" for j in range(self.num_classes)))
        return "\n".join(lines)
