"""Per-output binary evaluation (reference
``org.nd4j.evaluation.classification.EvaluationBinary``): independent
TP/FP/TN/FN + accuracy/precision/recall/F1 per output column at a 0.5 (or
custom) decision threshold."""

from __future__ import annotations

from typing import Optional

import numpy as np


class EvaluationBinary:
    def __init__(self, n_columns: Optional[int] = None, decision_threshold: float = 0.5):
        self.threshold = float(decision_threshold)
        self.n_columns = n_columns
        self.tp = self.fp = self.tn = self.fn = None
        if n_columns:
            self._init(n_columns)

    def _init(self, c):
        self.n_columns = c
        self.tp = np.zeros(c, np.int64)
        self.fp = np.zeros(c, np.int64)
        self.tn = np.zeros(c, np.int64)
        self.fn = np.zeros(c, np.int64)

    def eval(self, labels: np.ndarray, predictions: np.ndarray,
             mask: Optional[np.ndarray] = None) -> None:
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 1:
            labels, predictions = labels[:, None], predictions[:, None]
        if self.tp is None:
            self._init(labels.shape[1])
        pred = predictions >= self.threshold
        lab = labels > 0.5
        if mask is not None:
            m = np.asarray(mask).astype(bool)
            if m.ndim == 1:
                m = m[:, None]
            valid = np.broadcast_to(m, lab.shape)
        else:
            valid = np.ones_like(lab, bool)
        self.tp += (pred & lab & valid).sum(0)
        self.fp += (pred & ~lab & valid).sum(0)
        self.tn += (~pred & ~lab & valid).sum(0)
        self.fn += (~pred & lab & valid).sum(0)

    def accuracy(self, col: int = 0) -> float:
        tot = self.tp[col] + self.fp[col] + self.tn[col] + self.fn[col]
        return float((self.tp[col] + self.tn[col]) / tot) if tot else float("nan")

    def precision(self, col: int = 0) -> float:
        d = self.tp[col] + self.fp[col]
        return float(self.tp[col] / d) if d else 0.0

    def recall(self, col: int = 0) -> float:
        d = self.tp[col] + self.fn[col]
        return float(self.tp[col] / d) if d else 0.0

    def f1(self, col: int = 0) -> float:
        p, r = self.precision(col), self.recall(col)
        return 2 * p * r / (p + r) if p + r > 0 else 0.0

    def stats(self) -> str:
        lines = ["================Binary Evaluation================",
                 f"{'col':>5}{'acc':>10}{'prec':>10}{'recall':>10}{'F1':>10}"]
        for c in range(self.n_columns or 0):
            lines.append(f"{c:>5}{self.accuracy(c):>10.4f}{self.precision(c):>10.4f}"
                         f"{self.recall(c):>10.4f}{self.f1(c):>10.4f}")
        return "\n".join(lines)
