"""Evaluation suite.

Rebuild of upstream ``org.nd4j.evaluation`` (moved from DL4J to nd4j in
beta4): ``Evaluation`` (confusion/precision/recall/F1/top-N), ``ROC`` /
``ROCBinary`` / ``ROCMultiClass`` (exact + thresholded AUC),
``RegressionEvaluation`` (MSE/MAE/RMSE/R²), ``EvaluationBinary``,
``EvaluationCalibration`` (reliability diagrams). Accumulation is
numpy-on-host: evaluation runs between jitted inference calls, off the
device's critical path.
"""

from deeplearning4j_tpu.evaluation.evaluation import Evaluation
from deeplearning4j_tpu.evaluation.regression import RegressionEvaluation
from deeplearning4j_tpu.evaluation.roc import ROC, ROCBinary, ROCMultiClass
from deeplearning4j_tpu.evaluation.binary import EvaluationBinary
from deeplearning4j_tpu.evaluation.calibration import EvaluationCalibration

__all__ = ["Evaluation", "RegressionEvaluation", "ROC", "ROCBinary", "ROCMultiClass",
           "EvaluationBinary", "EvaluationCalibration"]
