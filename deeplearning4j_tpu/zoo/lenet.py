"""LeNet (reference ``org.deeplearning4j.zoo.model.LeNet``) — BASELINE
config #1's model: conv(20,5x5) → pool → conv(50,5x5) → pool → dense(500) →
softmax(10)."""

from deeplearning4j_tpu.nn import (ConvolutionLayer, DenseLayer, InputType,
                                   NeuralNetConfiguration, OutputLayer, SubsamplingLayer)
from deeplearning4j_tpu.train.updaters import Adam
from deeplearning4j_tpu.zoo.base import ZooModel


class LeNet(ZooModel):
    def __init__(self, num_classes: int = 10, seed: int = 123,
                 height: int = 28, width: int = 28, channels: int = 1,
                 updater=None):
        super().__init__(num_classes=num_classes, seed=seed)
        self.height, self.width, self.channels = height, width, channels
        self.updater = updater or Adam(1e-3)

    def conf(self):
        return (NeuralNetConfiguration.builder()
                .seed(self.seed)
                .updater(self.updater)
                .list()
                .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5), stride=(1, 1),
                                        activation="relu", convolution_mode="same"))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=50, kernel_size=(5, 5), stride=(1, 1),
                                        activation="relu", convolution_mode="same"))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(DenseLayer(n_out=500, activation="relu"))
                .layer(OutputLayer(n_out=self.num_classes, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.convolutional_flat(
                    self.height, self.width, self.channels))
                .build())
