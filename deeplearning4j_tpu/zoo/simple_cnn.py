"""SimpleCNN (reference ``org.deeplearning4j.zoo.model.SimpleCNN``)."""

from deeplearning4j_tpu.nn import (BatchNormalization, ConvolutionLayer, DenseLayer,
                                   DropoutLayer, InputType, NeuralNetConfiguration,
                                   OutputLayer, SubsamplingLayer)
from deeplearning4j_tpu.train.updaters import Adam
from deeplearning4j_tpu.zoo.base import ZooModel


class SimpleCNN(ZooModel):
    def __init__(self, num_classes: int = 10, seed: int = 123,
                 height: int = 48, width: int = 48, channels: int = 3):
        super().__init__(num_classes=num_classes, seed=seed)
        self.height, self.width, self.channels = height, width, channels

    def conf(self):
        return (NeuralNetConfiguration.builder()
                .seed(self.seed)
                .updater(Adam(1e-3))
                .list()
                .layer(ConvolutionLayer(n_out=16, kernel_size=(3, 3),
                                        convolution_mode="same", activation="relu"))
                .layer(BatchNormalization())
                .layer(ConvolutionLayer(n_out=32, kernel_size=(3, 3),
                                        convolution_mode="same", activation="relu"))
                .layer(BatchNormalization())
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=64, kernel_size=(3, 3),
                                        convolution_mode="same", activation="relu"))
                .layer(BatchNormalization())
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(DropoutLayer(dropout=0.5))
                .layer(DenseLayer(n_out=256, activation="relu"))
                .layer(OutputLayer(n_out=self.num_classes, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.convolutional(
                    self.height, self.width, self.channels))
                .build())
