"""ResNet-50 (reference ``org.deeplearning4j.zoo.model.ResNet50``) — BASELINE
config #2's model: ComputationGraph with bottleneck residual blocks
(conv/identity shortcut variants), batch norm after every conv, NHWC/bf16-
friendly for the MXU.

Structure (matching the reference's block plan): stem 7x7/2 + maxpool 3x3/2,
then stages [3, 4, 6, 3] of bottleneck blocks with widths
(64,64,256) (128,128,512) (256,256,1024) (512,512,2048), global average pool,
softmax head.
"""

from deeplearning4j_tpu.nn import (BatchNormalization, ConvolutionLayer,
                                   GlobalPoolingLayer, InputType, OutputLayer,
                                   PoolingType, SubsamplingLayer)
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph_vertices import ElementWiseVertex
from deeplearning4j_tpu.train.updaters import Nesterovs
from deeplearning4j_tpu.zoo.base import ZooModel

_STAGES = [(3, 64, 256), (4, 128, 512), (6, 256, 1024), (3, 512, 2048)]


class ResNet50(ZooModel):
    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 height: int = 224, width: int = 224, channels: int = 3,
                 updater=None):
        super().__init__(num_classes=num_classes, seed=seed)
        self.height, self.width, self.channels = height, width, channels
        self.updater = updater or Nesterovs(1e-1, momentum=0.9)

    def _bottleneck(self, g, name: str, inp: str, mid: int, out: int,
                    stride: int, project: bool) -> str:
        """One bottleneck block: 1x1(mid)/s -> 3x3(mid) -> 1x1(out), shortcut
        (projected 1x1/s if dimensions change), add, relu."""
        s = (stride, stride)
        g.add_layer(f"{name}_c1", ConvolutionLayer(
            n_out=mid, kernel_size=(1, 1), stride=s, activation="identity",
            has_bias=False), inp)
        g.add_layer(f"{name}_b1", BatchNormalization(activation="relu"), f"{name}_c1")
        g.add_layer(f"{name}_c2", ConvolutionLayer(
            n_out=mid, kernel_size=(3, 3), convolution_mode="same",
            activation="identity", has_bias=False), f"{name}_b1")
        g.add_layer(f"{name}_b2", BatchNormalization(activation="relu"), f"{name}_c2")
        g.add_layer(f"{name}_c3", ConvolutionLayer(
            n_out=out, kernel_size=(1, 1), activation="identity", has_bias=False),
            f"{name}_b2")
        g.add_layer(f"{name}_b3", BatchNormalization(), f"{name}_c3")
        shortcut = inp
        if project:
            g.add_layer(f"{name}_sc", ConvolutionLayer(
                n_out=out, kernel_size=(1, 1), stride=s, activation="identity",
                has_bias=False), inp)
            g.add_layer(f"{name}_sb", BatchNormalization(), f"{name}_sc")
            shortcut = f"{name}_sb"
        g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), f"{name}_b3", shortcut)
        from deeplearning4j_tpu.nn import ActivationLayer
        g.add_layer(f"{name}_out", ActivationLayer(activation="relu"), f"{name}_add")
        return f"{name}_out"

    def conf(self):
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(self.updater)
             .weight_init("relu")
             .graph_builder()
             .add_inputs("input"))
        g.add_layer("stem_conv", ConvolutionLayer(
            n_out=64, kernel_size=(7, 7), stride=(2, 2), convolution_mode="same",
            activation="identity", has_bias=False), "input")
        g.add_layer("stem_bn", BatchNormalization(activation="relu"), "stem_conv")
        g.add_layer("stem_pool", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2), convolution_mode="same"), "stem_bn")
        prev = "stem_pool"
        for stage, (blocks, mid, out) in enumerate(_STAGES):
            for block in range(blocks):
                stride = 2 if (block == 0 and stage > 0) else 1
                prev = self._bottleneck(
                    g, f"s{stage}b{block}", prev, mid, out,
                    stride=stride, project=(block == 0))
        g.add_layer("avgpool", GlobalPoolingLayer(pooling_type=PoolingType.AVG), prev)
        g.add_layer("fc", OutputLayer(n_out=self.num_classes, activation="softmax",
                                      loss="mcxent"), "avgpool")
        g.set_outputs("fc")
        g.set_input_types(InputType.convolutional(self.height, self.width, self.channels))
        return g.build()
