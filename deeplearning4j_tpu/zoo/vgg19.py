"""VGG19 (reference ``org.deeplearning4j.zoo.model.VGG19``): VGG16 with
deeper conv blocks (4 convs in blocks 3-5)."""

from deeplearning4j_tpu.nn import (ConvolutionLayer, DenseLayer, InputType,
                                   NeuralNetConfiguration, OutputLayer, SubsamplingLayer)
from deeplearning4j_tpu.train.updaters import Nesterovs
from deeplearning4j_tpu.zoo.base import ZooModel

_BLOCKS = [(2, 64), (2, 128), (4, 256), (4, 512), (4, 512)]


class VGG19(ZooModel):
    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 height: int = 224, width: int = 224, channels: int = 3):
        super().__init__(num_classes=num_classes, seed=seed)
        self.height, self.width, self.channels = height, width, channels

    def conf(self):
        b = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(Nesterovs(1e-2, momentum=0.9))
             .list())
        for n_convs, ch in _BLOCKS:
            for _ in range(n_convs):
                b.layer(ConvolutionLayer(n_out=ch, kernel_size=(3, 3),
                                         convolution_mode="same", activation="relu"))
            b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        return (b.layer(DenseLayer(n_out=4096, activation="relu"))
                .layer(DenseLayer(n_out=4096, activation="relu"))
                .layer(OutputLayer(n_out=self.num_classes, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.convolutional(
                    self.height, self.width, self.channels))
                .build())
