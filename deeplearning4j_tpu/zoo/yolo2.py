"""TinyYOLO and YOLO2 (reference ``org.deeplearning4j.zoo.model.TinyYOLO`` /
``YOLO2``): Darknet backbones with a ``Yolo2OutputLayer`` detection head.

YOLO2 adds the passthrough route: the 26x26x512 feature map is reorganised
with space-to-depth to 13x13x2048 and concatenated with the deep path before
the final detection conv — a ComputationGraph, as in the reference.
"""

from deeplearning4j_tpu.nn import (BatchNormalization, ConvolutionLayer, InputType,
                                   NeuralNetConfiguration, SpaceToDepthLayer,
                                   SubsamplingLayer, Yolo2OutputLayer)
from deeplearning4j_tpu.nn.graph_vertices import MergeVertex
from deeplearning4j_tpu.train.updaters import Nesterovs
from deeplearning4j_tpu.zoo.base import ZooModel
from deeplearning4j_tpu.zoo.darknet19 import _conv_bn as _dn_conv_bn

# default anchor priors (reference uses the VOC-trained priors)
_TINY_ANCHORS = ((1.08, 1.19), (3.42, 4.41), (6.63, 11.38), (9.42, 5.11),
                 (16.62, 10.52))
_YOLO2_ANCHORS = ((0.57273, 0.677385), (1.87446, 2.06253), (3.33843, 5.47434),
                  (7.88282, 3.52778), (9.77052, 9.16828))


def _conv_bn(b, n_out, k=3):
    _dn_conv_bn(b, n_out, k)


class TinyYOLO(ZooModel):
    def __init__(self, num_classes: int = 20, seed: int = 123,
                 height: int = 416, width: int = 416, channels: int = 3,
                 anchors=_TINY_ANCHORS):
        super().__init__(num_classes=num_classes, seed=seed)
        self.height, self.width, self.channels = height, width, channels
        self.anchors = anchors

    def conf(self):
        b = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(Nesterovs(1e-3, momentum=0.9))
             .list())
        for i, ch in enumerate((16, 32, 64, 128, 256)):
            _conv_bn(b, ch)
            b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        _conv_bn(b, 512)
        # stride-1 "same" pool (reference keeps 13x13 here)
        b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(1, 1),
                                 convolution_mode="same"))
        _conv_bn(b, 1024)
        _conv_bn(b, 1024)
        n_box = len(self.anchors) * (5 + self.num_classes)
        b.layer(ConvolutionLayer(n_out=n_box, kernel_size=(1, 1),
                                 activation="identity"))
        b.layer(Yolo2OutputLayer(anchors=tuple(self.anchors),
                                 n_classes=self.num_classes))
        return (b.set_input_type(InputType.convolutional(
                    self.height, self.width, self.channels))
                .build())


class YOLO2(ZooModel):
    def __init__(self, num_classes: int = 80, seed: int = 123,
                 height: int = 416, width: int = 416, channels: int = 3,
                 anchors=_YOLO2_ANCHORS):
        super().__init__(num_classes=num_classes, seed=seed)
        self.height, self.width, self.channels = height, width, channels
        self.anchors = anchors

    def _conv_bn(self, g, name, inp, ch, k=3):
        g.add_layer(name, ConvolutionLayer(
            n_out=ch, kernel_size=(k, k), convolution_mode="same",
            activation="identity", has_bias=False), inp)
        g.add_layer(f"{name}_bn", BatchNormalization(activation="leakyrelu"), name)
        return f"{name}_bn"

    def conf(self):
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(Nesterovs(1e-3, momentum=0.9))
             .graph_builder()
             .add_inputs("input"))
        p = self._conv_bn(g, "c1", "input", 32)
        g.add_layer("p1", SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)), p)
        p = self._conv_bn(g, "c2", "p1", 64)
        g.add_layer("p2", SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)), p)
        for i, ch in ((3, 128), (4, 256)):
            p = self._conv_bn(g, f"c{i}a", f"p{i - 1}", ch)
            p = self._conv_bn(g, f"c{i}b", p, ch // 2, k=1)
            p = self._conv_bn(g, f"c{i}c", p, ch)
            g.add_layer(f"p{i}", SubsamplingLayer(
                kernel_size=(2, 2), stride=(2, 2)), p)
        p = self._conv_bn(g, "c5a", "p4", 512)
        p = self._conv_bn(g, "c5b", p, 256, k=1)
        p = self._conv_bn(g, "c5c", p, 512)
        p = self._conv_bn(g, "c5d", p, 256, k=1)
        route = self._conv_bn(g, "c5e", p, 512)  # 26x26x512 passthrough source
        g.add_layer("p5", SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)),
                    route)
        p = self._conv_bn(g, "c6a", "p5", 1024)
        p = self._conv_bn(g, "c6b", p, 512, k=1)
        p = self._conv_bn(g, "c6c", p, 1024)
        p = self._conv_bn(g, "c6d", p, 512, k=1)
        p = self._conv_bn(g, "c6e", p, 1024)
        p = self._conv_bn(g, "c7a", p, 1024)
        deep = self._conv_bn(g, "c7b", p, 1024)
        # passthrough: 1x1 squeeze then space-to-depth 2x, concat with deep path
        pt = self._conv_bn(g, "pt_conv", route, 64, k=1)
        g.add_layer("pt_s2d", SpaceToDepthLayer(block_size=2), pt)
        g.add_vertex("route_cat", MergeVertex(), "pt_s2d", deep)
        p = self._conv_bn(g, "c8", "route_cat", 1024)
        n_box = len(self.anchors) * (5 + self.num_classes)
        g.add_layer("det_conv", ConvolutionLayer(
            n_out=n_box, kernel_size=(1, 1), activation="identity"), p)
        g.add_layer("yolo", Yolo2OutputLayer(anchors=tuple(self.anchors),
                                             n_classes=self.num_classes),
                    "det_conv")
        g.set_outputs("yolo")
        g.set_input_types(InputType.convolutional(
            self.height, self.width, self.channels))
        return g.build()
