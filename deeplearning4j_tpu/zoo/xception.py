"""Xception (reference ``org.deeplearning4j.zoo.model.Xception``).

Entry flow (strided separable-conv blocks with 1x1 residual projections),
middle flow (8 identity separable blocks), exit flow — all depthwise-
separable convs, built as a ComputationGraph exactly as the reference does.
"""

from deeplearning4j_tpu.nn import (ActivationLayer, BatchNormalization,
                                   ConvolutionLayer, GlobalPoolingLayer,
                                   InputType, OutputLayer, PoolingType,
                                   SeparableConvolution2D, SubsamplingLayer)
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph_vertices import ElementWiseVertex
from deeplearning4j_tpu.train.updaters import Nesterovs
from deeplearning4j_tpu.zoo.base import ZooModel


class Xception(ZooModel):
    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 height: int = 299, width: int = 299, channels: int = 3,
                 middle_blocks: int = 8):
        super().__init__(num_classes=num_classes, seed=seed)
        self.height, self.width, self.channels = height, width, channels
        self.middle_blocks = middle_blocks

    def _sep_bn(self, g, name, inp, ch, act_first=True):
        """[relu] -> sepconv 3x3 -> bn"""
        src = inp
        if act_first:
            g.add_layer(f"{name}_act", ActivationLayer(activation="relu"), src)
            src = f"{name}_act"
        g.add_layer(f"{name}_sep", SeparableConvolution2D(
            n_out=ch, kernel_size=(3, 3), convolution_mode="same",
            activation="identity", has_bias=False), src)
        g.add_layer(f"{name}_bn", BatchNormalization(), f"{name}_sep")
        return f"{name}_bn"

    def _entry_block(self, g, name, inp, ch, first_act=True):
        """Two sep-convs + maxpool, with a strided 1x1 conv residual."""
        a = self._sep_bn(g, f"{name}_1", inp, ch, act_first=first_act)
        b = self._sep_bn(g, f"{name}_2", a, ch)
        g.add_layer(f"{name}_pool", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2), convolution_mode="same"), b)
        g.add_layer(f"{name}_res", ConvolutionLayer(
            n_out=ch, kernel_size=(1, 1), stride=(2, 2), activation="identity",
            has_bias=False), inp)
        g.add_layer(f"{name}_resbn", BatchNormalization(), f"{name}_res")
        g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"),
                     f"{name}_pool", f"{name}_resbn")
        return f"{name}_add"

    def conf(self):
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(Nesterovs(4.5e-2, momentum=0.9))
             .weight_init("relu")
             .graph_builder()
             .add_inputs("input"))
        # stem
        g.add_layer("stem_c1", ConvolutionLayer(
            n_out=32, kernel_size=(3, 3), stride=(2, 2), activation="identity",
            has_bias=False), "input")
        g.add_layer("stem_b1", BatchNormalization(activation="relu"), "stem_c1")
        g.add_layer("stem_c2", ConvolutionLayer(
            n_out=64, kernel_size=(3, 3), activation="identity", has_bias=False),
            "stem_b1")
        g.add_layer("stem_b2", BatchNormalization(activation="relu"), "stem_c2")
        # entry flow
        prev = self._entry_block(g, "entry1", "stem_b2", 128, first_act=False)
        prev = self._entry_block(g, "entry2", prev, 256)
        prev = self._entry_block(g, "entry3", prev, 728)
        # middle flow: identity residual, three sep-convs each
        for i in range(self.middle_blocks):
            name = f"mid{i}"
            a = self._sep_bn(g, f"{name}_1", prev, 728)
            b = self._sep_bn(g, f"{name}_2", a, 728)
            c = self._sep_bn(g, f"{name}_3", b, 728)
            g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), c, prev)
            prev = f"{name}_add"
        # exit flow
        a = self._sep_bn(g, "exit_1", prev, 728)
        b = self._sep_bn(g, "exit_2", a, 1024)
        g.add_layer("exit_pool", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2), convolution_mode="same"), b)
        g.add_layer("exit_res", ConvolutionLayer(
            n_out=1024, kernel_size=(1, 1), stride=(2, 2), activation="identity",
            has_bias=False), prev)
        g.add_layer("exit_resbn", BatchNormalization(), "exit_res")
        g.add_vertex("exit_add", ElementWiseVertex(op="add"),
                     "exit_pool", "exit_resbn")
        c = self._sep_bn(g, "exit_3", "exit_add", 1536, act_first=False)
        g.add_layer("exit_3_relu", ActivationLayer(activation="relu"), c)
        d = self._sep_bn(g, "exit_4", "exit_3_relu", 2048, act_first=False)
        g.add_layer("exit_4_relu", ActivationLayer(activation="relu"), d)
        g.add_layer("avgpool", GlobalPoolingLayer(pooling_type=PoolingType.AVG),
                    "exit_4_relu")
        g.add_layer("out", OutputLayer(n_out=self.num_classes,
                                       activation="softmax", loss="mcxent"),
                    "avgpool")
        g.set_outputs("out")
        g.set_input_types(InputType.convolutional(
            self.height, self.width, self.channels))
        return g.build()
