"""Darknet19 (reference ``org.deeplearning4j.zoo.model.Darknet19`` — the
YOLO9000 backbone)."""

from deeplearning4j_tpu.nn import (BatchNormalization, ConvolutionLayer,
                                   GlobalPoolingLayer, InputType,
                                   NeuralNetConfiguration, OutputLayer, PoolingType,
                                   SubsamplingLayer)
from deeplearning4j_tpu.train.updaters import Nesterovs
from deeplearning4j_tpu.zoo.base import ZooModel


def _conv_bn(b, n_out, k):
    b.layer(ConvolutionLayer(n_out=n_out, kernel_size=(k, k),
                             convolution_mode="same", activation="identity",
                             has_bias=False))
    b.layer(BatchNormalization(activation="leakyrelu"))


class Darknet19(ZooModel):
    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 height: int = 224, width: int = 224, channels: int = 3):
        super().__init__(num_classes=num_classes, seed=seed)
        self.height, self.width, self.channels = height, width, channels

    def conf(self):
        b = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(Nesterovs(1e-2, momentum=0.9))
             .list())
        _conv_bn(b, 32, 3)
        b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        _conv_bn(b, 64, 3)
        b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        for ch in (128, 256):
            _conv_bn(b, ch, 3)
            _conv_bn(b, ch // 2, 1)
            _conv_bn(b, ch, 3)
            b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        for ch in (512, 1024):
            _conv_bn(b, ch, 3)
            _conv_bn(b, ch // 2, 1)
            _conv_bn(b, ch, 3)
            _conv_bn(b, ch // 2, 1)
            _conv_bn(b, ch, 3)
            if ch == 512:
                b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        b.layer(ConvolutionLayer(n_out=self.num_classes, kernel_size=(1, 1),
                                 activation="identity"))
        b.layer(GlobalPoolingLayer(pooling_type=PoolingType.AVG))
        b.layer(OutputLayer(n_out=self.num_classes, n_in=self.num_classes,
                            activation="softmax", loss="mcxent", has_bias=False,
                            weight_init="identity"))
        return (b.set_input_type(InputType.convolutional(
                    self.height, self.width, self.channels))
                .build())
