"""ZooModel SPI (reference ``org.deeplearning4j.zoo.ZooModel``)."""

from __future__ import annotations

import os
from typing import Optional


class ZooModel:
    """Subclasses implement ``conf()`` (and optionally ``graph_conf()``) and
    set ``input_shape``/``num_classes``."""

    def __init__(self, num_classes: int = 1000, seed: int = 123, **kwargs):
        self.num_classes = num_classes
        self.seed = seed
        self.kwargs = kwargs

    def conf(self):
        raise NotImplementedError

    def init(self):
        """Build + init the network."""
        conf = self.conf()
        from deeplearning4j_tpu.models.computation_graph import ComputationGraphConfiguration
        if isinstance(conf, ComputationGraphConfiguration):
            from deeplearning4j_tpu.models.computation_graph import ComputationGraph
            return ComputationGraph(conf).init()
        from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
        return MultiLayerNetwork(conf).init()

    # -- pretrained weights: offline-first (reference downloads; we load local)
    def pretrained_path(self) -> Optional[str]:
        root = os.environ.get("DL4J_TPU_ZOO_DIR",
                              os.path.expanduser("~/.deeplearning4j_tpu/zoo"))
        p = os.path.join(root, f"{type(self).__name__.lower()}.zip")
        return p if os.path.exists(p) else None

    def init_pretrained(self):
        path = self.pretrained_path()
        if path is None:
            raise FileNotFoundError(
                f"No pretrained archive for {type(self).__name__}; place a model zip "
                "under $DL4J_TPU_ZOO_DIR (offline environment — no download mirror)")
        from deeplearning4j_tpu.models.serializer import ModelSerializer
        try:
            return ModelSerializer.restore_computation_graph(path)
        except Exception:
            return ModelSerializer.restore_multi_layer_network(path)
