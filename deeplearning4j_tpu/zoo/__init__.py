"""Model zoo.

Rebuild of upstream ``org.deeplearning4j.zoo``: ``ZooModel`` SPI with LeNet,
SimpleCNN, AlexNet, VGG16, ResNet-50, Darknet19, TinyYOLO-style backbone,
UNet, TextGenerationLSTM — plus BERT (the reference reaches BERT only through
TF import; here it is first-class, built on the transformer layers).

Each zoo model is a config factory: ``init()`` returns a ready
``MultiLayerNetwork``/``ComputationGraph`` built from the same builder DSL a
user would write, so the zoo doubles as an API test surface (reference
``TestInstantiation`` pattern). ``init_pretrained()`` loads weights from a
local archive path (offline environment; the reference downloads from Azure).
"""

from deeplearning4j_tpu.zoo.base import ZooModel
from deeplearning4j_tpu.zoo.lenet import LeNet
from deeplearning4j_tpu.zoo.simple_cnn import SimpleCNN
from deeplearning4j_tpu.zoo.alexnet import AlexNet
from deeplearning4j_tpu.zoo.vgg16 import VGG16
from deeplearning4j_tpu.zoo.resnet50 import ResNet50
from deeplearning4j_tpu.zoo.unet import UNet
from deeplearning4j_tpu.zoo.darknet19 import Darknet19
from deeplearning4j_tpu.zoo.textgen_lstm import TextGenerationLSTM
from deeplearning4j_tpu.zoo.bert import Bert
from deeplearning4j_tpu.zoo.vgg19 import VGG19
from deeplearning4j_tpu.zoo.squeezenet import SqueezeNet
from deeplearning4j_tpu.zoo.xception import Xception
from deeplearning4j_tpu.zoo.inception_resnet import InceptionResNetV1
from deeplearning4j_tpu.zoo.yolo2 import TinyYOLO, YOLO2

__all__ = ["ZooModel", "LeNet", "SimpleCNN", "AlexNet", "VGG16", "VGG19",
           "ResNet50", "UNet", "Darknet19", "TextGenerationLSTM", "Bert",
           "SqueezeNet", "Xception", "InceptionResNetV1", "TinyYOLO", "YOLO2"]
