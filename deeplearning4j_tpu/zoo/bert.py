"""BERT (BASELINE config #4's model; the reference reaches it through TF
import — SURVEY.md §3.3 — here it is a first-class zoo model built from the
framework's own transformer layers).

``Bert.base()`` is BERT-base (L=12, H=768, A=12); smaller presets exist for
testing. The classification variant appends [CLS] pooling + tanh pooler +
softmax head (the SST-2 fine-tune shape). Masks: pass the padding mask as
``features_mask`` — attention consumes it as a key-side mask.
"""

from deeplearning4j_tpu.nn import (InputType, NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.nn.attention_layers import (BertEmbeddingLayer, ClsPoolingLayer,
                                                    TransformerEncoderBlock,
                                                    TransformerEncoderStack)
from deeplearning4j_tpu.nn.core_layers import DenseLayer
from deeplearning4j_tpu.train.updaters import Adam
from deeplearning4j_tpu.zoo.base import ZooModel


class Bert(ZooModel):
    def __init__(self, vocab_size: int = 30522, d_model: int = 768,
                 n_layers: int = 12, n_heads: int = 12, ffn_size: int = 3072,
                 max_len: int = 512, num_classes: int = 2, seed: int = 123,
                 dropout_rate: float = 0.1, updater=None,
                 stacked: bool = False):
        super().__init__(num_classes=num_classes, seed=seed)
        self.vocab_size = vocab_size
        self.d_model = d_model
        self.n_layers = n_layers
        self.n_heads = n_heads
        self.ffn_size = ffn_size
        self.max_len = max_len
        self.dropout_rate = dropout_rate
        self.updater = updater or Adam(2e-5)
        # scan-over-layers stacked encoder (opt-in): ~16 parameter arrays
        # instead of ~200 and ~3x faster compiles, BUT measured 48 vs
        # 37 ms/step on v5e at BERT-base shape — lax.scan blocks XLA's
        # inter-layer fusion/overlap and the scan backward stacks extra
        # residual copies. Useful when compile time or dispatch marshaling
        # dominates (very deep stacks, high-latency links); default off
        self.stacked = stacked

    @staticmethod
    def base(num_classes: int = 2, **kw) -> "Bert":
        return Bert(d_model=768, n_layers=12, n_heads=12, ffn_size=3072,
                    num_classes=num_classes, **kw)

    @staticmethod
    def small(num_classes: int = 2, **kw) -> "Bert":
        """BERT-small-ish for tests: L=2, H=128, A=2."""
        kw.setdefault("vocab_size", 1000)
        return Bert(d_model=128, n_layers=2, n_heads=2, ffn_size=256,
                    max_len=128, num_classes=num_classes, **kw)

    def conf(self):
        b = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(self.updater)
             .weight_init("xavier")
             .list()
             .layer(BertEmbeddingLayer(
                 vocab_size=self.vocab_size, d_model=self.d_model,
                 max_len=self.max_len, dropout_rate=self.dropout_rate)))
        if self.stacked:
            b.layer(TransformerEncoderStack(
                n_layers=self.n_layers, n_heads=self.n_heads,
                ffn_size=self.ffn_size, dropout_rate=self.dropout_rate))
        else:
            for _ in range(self.n_layers):
                b.layer(TransformerEncoderBlock(
                    n_heads=self.n_heads, ffn_size=self.ffn_size,
                    dropout_rate=self.dropout_rate))
        return (b.layer(ClsPoolingLayer())
                .layer(DenseLayer(n_out=self.d_model, activation="tanh"))  # pooler
                .layer(OutputLayer(n_out=self.num_classes, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.recurrent(1))  # int token ids (b, t)
                .build())
