"""SqueezeNet v1.1 (reference ``org.deeplearning4j.zoo.model.SqueezeNet``).

Fire modules: a 1x1 "squeeze" conv feeding parallel 1x1 and 3x3 "expand"
convs whose outputs concatenate on the channel axis (MergeVertex) — the
reference builds the same DAG as a ComputationGraph.
"""

from deeplearning4j_tpu.nn import (ConvolutionLayer, GlobalPoolingLayer, InputType,
                                   LossLayer, PoolingType, SubsamplingLayer)
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph_vertices import MergeVertex
from deeplearning4j_tpu.train.updaters import Nesterovs
from deeplearning4j_tpu.zoo.base import ZooModel

# (squeeze, expand) channel plan for fire2..fire9 (v1.1)
_FIRES = [(16, 64), (16, 64), (32, 128), (32, 128),
          (48, 192), (48, 192), (64, 256), (64, 256)]
# maxpool after these fire indices (0-based into _FIRES), v1.1 placement
_POOL_AFTER = {1, 3}


class SqueezeNet(ZooModel):
    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 height: int = 224, width: int = 224, channels: int = 3,
                 updater=None):
        super().__init__(num_classes=num_classes, seed=seed)
        self.height, self.width, self.channels = height, width, channels
        self.updater = updater or Nesterovs(1e-3, momentum=0.9)

    def _fire(self, g, name: str, inp: str, squeeze: int, expand: int) -> str:
        g.add_layer(f"{name}_sq", ConvolutionLayer(
            n_out=squeeze, kernel_size=(1, 1), activation="relu"), inp)
        g.add_layer(f"{name}_e1", ConvolutionLayer(
            n_out=expand, kernel_size=(1, 1), activation="relu"), f"{name}_sq")
        g.add_layer(f"{name}_e3", ConvolutionLayer(
            n_out=expand, kernel_size=(3, 3), convolution_mode="same",
            activation="relu"), f"{name}_sq")
        g.add_vertex(f"{name}_cat", MergeVertex(), f"{name}_e1", f"{name}_e3")
        return f"{name}_cat"

    def conf(self):
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(self.updater)
             .weight_init("relu")
             .graph_builder()
             .add_inputs("input"))
        g.add_layer("conv1", ConvolutionLayer(
            n_out=64, kernel_size=(3, 3), stride=(2, 2), activation="relu"),
            "input")
        g.add_layer("pool1", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2)), "conv1")
        prev = "pool1"
        for i, (sq, ex) in enumerate(_FIRES):
            prev = self._fire(g, f"fire{i + 2}", prev, sq, ex)
            if i in _POOL_AFTER:
                g.add_layer(f"pool{i + 2}", SubsamplingLayer(
                    kernel_size=(3, 3), stride=(2, 2)), prev)
                prev = f"pool{i + 2}"
        g.add_layer("conv10", ConvolutionLayer(
            n_out=self.num_classes, kernel_size=(1, 1), activation="relu"), prev)
        g.add_layer("avgpool", GlobalPoolingLayer(pooling_type=PoolingType.AVG),
                    "conv10")
        g.add_layer("out", LossLayer(activation="softmax", loss="mcxent"),
                    "avgpool")
        g.set_outputs("out")
        g.set_input_types(InputType.convolutional(
            self.height, self.width, self.channels))
        return g.build()
