"""AlexNet (reference ``org.deeplearning4j.zoo.model.AlexNet``)."""

from deeplearning4j_tpu.nn import (ConvolutionLayer, DenseLayer, DropoutLayer, InputType,
                                   LocalResponseNormalization, NeuralNetConfiguration,
                                   OutputLayer, SubsamplingLayer)
from deeplearning4j_tpu.train.updaters import Nesterovs
from deeplearning4j_tpu.zoo.base import ZooModel


class AlexNet(ZooModel):
    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 height: int = 224, width: int = 224, channels: int = 3):
        super().__init__(num_classes=num_classes, seed=seed)
        self.height, self.width, self.channels = height, width, channels

    def conf(self):
        return (NeuralNetConfiguration.builder()
                .seed(self.seed)
                .updater(Nesterovs(1e-2, momentum=0.9))
                .l2(5e-4)
                .list()
                .layer(ConvolutionLayer(n_out=96, kernel_size=(11, 11), stride=(4, 4),
                                        activation="relu"))
                .layer(LocalResponseNormalization())
                .layer(SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=256, kernel_size=(5, 5), stride=(1, 1),
                                        convolution_mode="same", activation="relu",
                                        bias_init=1.0))
                .layer(LocalResponseNormalization())
                .layer(SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=384, kernel_size=(3, 3),
                                        convolution_mode="same", activation="relu"))
                .layer(ConvolutionLayer(n_out=384, kernel_size=(3, 3),
                                        convolution_mode="same", activation="relu",
                                        bias_init=1.0))
                .layer(ConvolutionLayer(n_out=256, kernel_size=(3, 3),
                                        convolution_mode="same", activation="relu",
                                        bias_init=1.0))
                .layer(SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
                .layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
                .layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
                .layer(OutputLayer(n_out=self.num_classes, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.convolutional(
                    self.height, self.width, self.channels))
                .build())
