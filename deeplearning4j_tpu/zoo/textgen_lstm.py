"""TextGenerationLSTM (reference
``org.deeplearning4j.zoo.model.TextGenerationLSTM``) — BASELINE config #3's
family: char-RNN language model, stacked (Graves)LSTM + time-distributed
softmax, trained with truncated BPTT."""

from deeplearning4j_tpu.nn import (GravesLSTM, InputType, LSTM,
                                   NeuralNetConfiguration, RnnOutputLayer)
from deeplearning4j_tpu.train.updaters import RmsProp
from deeplearning4j_tpu.zoo.base import ZooModel


class TextGenerationLSTM(ZooModel):
    def __init__(self, vocab_size: int = 77, seed: int = 123,
                 hidden: int = 256, layers: int = 2, tbptt_length: int = 50,
                 graves: bool = False, updater=None):
        super().__init__(num_classes=vocab_size, seed=seed)
        self.vocab_size = vocab_size
        self.hidden = hidden
        self.layers = layers
        self.tbptt_length = tbptt_length
        self.graves = graves
        self.updater = updater or RmsProp(1e-3)

    def conf(self):
        cell = GravesLSTM if self.graves else LSTM
        b = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(self.updater)
             .list())
        for _ in range(self.layers):
            b.layer(cell(n_out=self.hidden, activation="tanh"))
        return (b.layer(RnnOutputLayer(n_out=self.vocab_size, activation="softmax",
                                       loss="mcxent"))
                .set_input_type(InputType.recurrent(self.vocab_size))
                .tbptt_fwd_length(self.tbptt_length)
                .tbptt_back_length(self.tbptt_length)
                .build())
