"""UNet (reference ``org.deeplearning4j.zoo.model.UNet``): encoder/decoder
segmentation net with skip connections — exercises Deconvolution2D and
MergeVertex in a ComputationGraph."""

from deeplearning4j_tpu.nn import (ConvolutionLayer, Deconvolution2D, InputType,
                                   LossLayer, SubsamplingLayer)
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph_vertices import MergeVertex
from deeplearning4j_tpu.train.updaters import Adam
from deeplearning4j_tpu.zoo.base import ZooModel


class UNet(ZooModel):
    def __init__(self, num_classes: int = 1, seed: int = 123,
                 height: int = 128, width: int = 128, channels: int = 3,
                 base_filters: int = 16, depth: int = 3):
        super().__init__(num_classes=num_classes, seed=seed)
        self.height, self.width, self.channels = height, width, channels
        self.base_filters = base_filters
        self.depth = depth

    def conf(self):
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(Adam(1e-3))
             .graph_builder()
             .add_inputs("input"))
        prev = "input"
        skips = []
        f = self.base_filters
        for d in range(self.depth):
            g.add_layer(f"enc{d}_c1", ConvolutionLayer(
                n_out=f << d, kernel_size=(3, 3), convolution_mode="same",
                activation="relu"), prev)
            g.add_layer(f"enc{d}_c2", ConvolutionLayer(
                n_out=f << d, kernel_size=(3, 3), convolution_mode="same",
                activation="relu"), f"enc{d}_c1")
            skips.append(f"enc{d}_c2")
            g.add_layer(f"enc{d}_pool", SubsamplingLayer(
                kernel_size=(2, 2), stride=(2, 2)), f"enc{d}_c2")
            prev = f"enc{d}_pool"
        g.add_layer("mid_c1", ConvolutionLayer(
            n_out=f << self.depth, kernel_size=(3, 3), convolution_mode="same",
            activation="relu"), prev)
        prev = "mid_c1"
        for d in reversed(range(self.depth)):
            g.add_layer(f"dec{d}_up", Deconvolution2D(
                n_out=f << d, kernel_size=(2, 2), stride=(2, 2),
                convolution_mode="same", activation="relu"), prev)
            g.add_vertex(f"dec{d}_merge", MergeVertex(), f"dec{d}_up", skips[d])
            g.add_layer(f"dec{d}_c1", ConvolutionLayer(
                n_out=f << d, kernel_size=(3, 3), convolution_mode="same",
                activation="relu"), f"dec{d}_merge")
            prev = f"dec{d}_c1"
        g.add_layer("head", ConvolutionLayer(
            n_out=self.num_classes, kernel_size=(1, 1), activation="identity"), prev)
        g.add_layer("out", LossLayer(loss="xent", activation="sigmoid"), "head")
        g.set_outputs("out")
        g.set_input_types(InputType.convolutional(self.height, self.width, self.channels))
        return g.build()
