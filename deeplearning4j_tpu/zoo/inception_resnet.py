"""InceptionResNetV1 (reference ``org.deeplearning4j.zoo.model.InceptionResNetV1``
— the FaceNet backbone).

Stem -> 5x inception-resnet-A -> reduction-A -> 10x inception-resnet-B ->
reduction-B -> 5x inception-resnet-C -> avgpool -> embedding head. Residual
branches are concatenated (MergeVertex), projected with a 1x1 conv, scaled
(ScaleVertex, the reference's residual damping), and added to the shortcut.
Block counts are configurable so tests can build a shallow variant.
"""

from deeplearning4j_tpu.nn import (ActivationLayer, BatchNormalization,
                                   ConvolutionLayer, GlobalPoolingLayer,
                                   InputType, OutputLayer, PoolingType,
                                   SubsamplingLayer)
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph_vertices import (ElementWiseVertex, MergeVertex,
                                                  ScaleVertex)
from deeplearning4j_tpu.train.updaters import RmsProp
from deeplearning4j_tpu.zoo.base import ZooModel


class InceptionResNetV1(ZooModel):
    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 height: int = 160, width: int = 160, channels: int = 3,
                 blocks_a: int = 5, blocks_b: int = 10, blocks_c: int = 5):
        super().__init__(num_classes=num_classes, seed=seed)
        self.height, self.width, self.channels = height, width, channels
        self.blocks_a, self.blocks_b, self.blocks_c = blocks_a, blocks_b, blocks_c

    def _conv(self, g, name, inp, ch, k, stride=1, same=True, act="relu"):
        g.add_layer(name, ConvolutionLayer(
            n_out=ch, kernel_size=(k, k) if isinstance(k, int) else k,
            stride=(stride, stride), convolution_mode="same" if same else "truncate",
            activation="identity", has_bias=False), inp)
        g.add_layer(f"{name}_bn", BatchNormalization(activation=act), name)
        return f"{name}_bn"

    def _residual(self, g, name, inp, branches, project_ch, scale=0.17):
        """Concat branches -> 1x1 project -> scale -> add(inp) -> relu."""
        g.add_vertex(f"{name}_cat", MergeVertex(), *branches)
        g.add_layer(f"{name}_proj", ConvolutionLayer(
            n_out=project_ch, kernel_size=(1, 1), activation="identity"),
            f"{name}_cat")
        g.add_vertex(f"{name}_scale", ScaleVertex(scale=scale), f"{name}_proj")
        g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"),
                     inp, f"{name}_scale")
        g.add_layer(f"{name}_relu", ActivationLayer(activation="relu"),
                    f"{name}_add")
        return f"{name}_relu"

    def _block_a(self, g, name, inp):  # 35x35, 256 ch
        b1 = self._conv(g, f"{name}_b1", inp, 32, 1)
        b2 = self._conv(g, f"{name}_b2b", self._conv(g, f"{name}_b2a", inp, 32, 1), 32, 3)
        b3a = self._conv(g, f"{name}_b3a", inp, 32, 1)
        b3b = self._conv(g, f"{name}_b3b", b3a, 32, 3)
        b3 = self._conv(g, f"{name}_b3c", b3b, 32, 3)
        return self._residual(g, name, inp, [b1, b2, b3], 256, scale=0.17)

    def _block_b(self, g, name, inp):  # 17x17, 896 ch
        b1 = self._conv(g, f"{name}_b1", inp, 128, 1)
        b2a = self._conv(g, f"{name}_b2a", inp, 128, 1)
        b2b = self._conv(g, f"{name}_b2b", b2a, 128, (1, 7))
        b2 = self._conv(g, f"{name}_b2c", b2b, 128, (7, 1))
        return self._residual(g, name, inp, [b1, b2], 896, scale=0.10)

    def _block_c(self, g, name, inp):  # 8x8, 1792 ch
        b1 = self._conv(g, f"{name}_b1", inp, 192, 1)
        b2a = self._conv(g, f"{name}_b2a", inp, 192, 1)
        b2b = self._conv(g, f"{name}_b2b", b2a, 192, (1, 3))
        b2 = self._conv(g, f"{name}_b2c", b2b, 192, (3, 1))
        return self._residual(g, name, inp, [b1, b2], 1792, scale=0.20)

    def conf(self):
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(RmsProp(0.1, rms_decay=0.96, epsilon=0.001))
             .weight_init("relu")
             .graph_builder()
             .add_inputs("input"))
        # stem: 149x149x32 -> ... -> 35x35x256
        p = self._conv(g, "stem1", "input", 32, 3, stride=2)
        p = self._conv(g, "stem2", p, 32, 3)
        p = self._conv(g, "stem3", p, 64, 3)
        g.add_layer("stem_pool", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2), convolution_mode="same"), p)
        p = self._conv(g, "stem4", "stem_pool", 80, 1)
        p = self._conv(g, "stem5", p, 192, 3)
        p = self._conv(g, "stem6", p, 256, 3, stride=2)
        for i in range(self.blocks_a):
            p = self._block_a(g, f"a{i}", p)
        # reduction-A: 35->17, 256->896
        ra1 = self._conv(g, "ra_b1", p, 384, 3, stride=2)
        ra2a = self._conv(g, "ra_b2a", p, 192, 1)
        ra2b = self._conv(g, "ra_b2b", ra2a, 192, 3)
        ra2 = self._conv(g, "ra_b2c", ra2b, 256, 3, stride=2)
        g.add_layer("ra_pool", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2), convolution_mode="same"), p)
        g.add_vertex("ra_cat", MergeVertex(), ra1, ra2, "ra_pool")
        p = "ra_cat"
        for i in range(self.blocks_b):
            p = self._block_b(g, f"b{i}", p)
        # reduction-B: 17->8, 896->1792
        rb1a = self._conv(g, "rb_b1a", p, 256, 1)
        rb1 = self._conv(g, "rb_b1b", rb1a, 384, 3, stride=2)
        rb2a = self._conv(g, "rb_b2a", p, 256, 1)
        rb2 = self._conv(g, "rb_b2b", rb2a, 256, 3, stride=2)
        rb3a = self._conv(g, "rb_b3a", p, 256, 1)
        rb3b = self._conv(g, "rb_b3b", rb3a, 256, 3)
        rb3 = self._conv(g, "rb_b3c", rb3b, 256, 3, stride=2)
        g.add_layer("rb_pool", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2), convolution_mode="same"), p)
        g.add_vertex("rb_cat", MergeVertex(), rb1, rb2, rb3, "rb_pool")
        p = "rb_cat"
        for i in range(self.blocks_c):
            p = self._block_c(g, f"c{i}", p)
        g.add_layer("avgpool", GlobalPoolingLayer(pooling_type=PoolingType.AVG), p)
        g.add_layer("out", OutputLayer(n_out=self.num_classes,
                                       activation="softmax", loss="mcxent"),
                    "avgpool")
        g.set_outputs("out")
        g.set_input_types(InputType.convolutional(
            self.height, self.width, self.channels))
        return g.build()
